"""The hierarchical-tier recovery story, end to end (VERDICT r4 item 5): train
with BOTH tiers, lose the entire local tier in the crash, restart — the same
callback seam restores from the Orbax global tier — and the rebuilt replication
group repopulates the local tier with coverage-complete saves.

Reference analogue: ``ptl_resiliency/local_checkpoint_callback.py:101-203``
(HierarchicalCheckpointIO's whole point is the global fallback) +
``base_manager.py:156-203`` coverage logic.
"""

import shutil

import jax.numpy as jnp
import numpy as np
import pytest

from tests.checkpoint.test_local import run_ranks
from tpu_resiliency.checkpoint.comm import PeerExchange, StoreComm
from tpu_resiliency.checkpoint.local_manager import LocalCheckpointManager
from tpu_resiliency.checkpoint.replication import CliqueReplicationStrategy
from tpu_resiliency.integrations import (
    HierarchicalCheckpointCallback,
    OrbaxCheckpointCallback,
)
from tpu_resiliency.integrations.loop import LoopContext, run_training
from tpu_resiliency.platform.store import CoordStore


def _step_fn(state, step):
    return {"w": state["w"] + 1.0, "step": jnp.asarray(step)}


def _init_state():
    return {"w": jnp.zeros((4,)), "step": jnp.asarray(0)}


def test_local_tier_lost_orbax_restores_replication_repopulates(tmp_path, kv_server):
    world = 4
    orbax_dir = str(tmp_path / "orbax")
    node_dir = lambda r: str(tmp_path / f"node{r}")  # per-rank "node-local disk"
    stores = []

    def make_store():
        s = CoordStore("127.0.0.1", kv_server.port, timeout=30.0)
        stores.append(s)
        return s

    # ---- phase 1: world 4, local saves every 2 steps (cliques [0,1],[2,3]),
    # rank 0 additionally writes the Orbax global tier every 3 steps.
    def train_phase(rank):
        comm = StoreComm(make_store(), rank, list(range(world)), timeout=30.0)
        ex = PeerExchange(make_store(), rank, timeout=30.0)
        ex.start()
        try:
            strat = CliqueReplicationStrategy(
                comm, ex, replication_jump=1, replication_factor=2
            )
            mgr = LocalCheckpointManager(
                node_dir(rank), rank=rank, comm=comm, replication=strat
            )
            local_cb = HierarchicalCheckpointCallback(
                local_manager=mgr, local_every=2
            )
            cbs = [local_cb]
            orbax_cb = None
            if rank == 0:
                orbax_cb = OrbaxCheckpointCallback(orbax_dir, every=3)
                cbs.append(orbax_cb)
            ctx = run_training(_step_fn, _init_state(), num_steps=4, callbacks=cbs)
            assert float(ctx.state["w"][0]) == 4.0
            assert mgr.find_latest() == 4  # iterations 2 and 4 saved, covered
            if orbax_cb is not None:
                assert orbax_cb.latest_step() == 2  # saved after step idx 2 (w=3)
                orbax_cb.close()
            mgr.close()
        finally:
            ex.close()

    run_ranks(world, train_phase, timeout=240.0)

    # ---- the crash: every node's local disk is lost (beyond any coverage),
    # and ranks 2/3 don't come back.
    for r in range(world):
        shutil.rmtree(node_dir(r))

    # ---- phase 2: survivors [0,1] restart with fresh processes. Managers come
    # up configured for the old world, adopt the survivor group through the
    # callback's rebuild seam, find the local tier unrestorable, fall back to
    # Orbax through the same seam, resume, and repopulate the local tier.
    survivors = [0, 1]

    def restart_phase(rank):
        stale_comm = StoreComm(make_store(), rank, list(range(world)), timeout=30.0)
        ex = PeerExchange(make_store(), rank, timeout=30.0)
        ex.start()
        try:
            strat = CliqueReplicationStrategy(
                stale_comm, ex, replication_jump=1, replication_factor=2
            )
            mgr = LocalCheckpointManager(
                node_dir(rank), rank=rank, comm=stale_comm, replication=strat
            )
            local_cb = HierarchicalCheckpointCallback(
                local_manager=mgr, local_every=2
            )
            new_comm = StoreComm(make_store(), rank, survivors, timeout=30.0, generation=1)
            local_cb.rebuild_group(new_comm)
            assert strat.my_group == survivors

            ctx = LoopContext()
            ctx.state = _init_state()
            # Local tier: gone beyond coverage — the seam must say so.
            assert local_cb.restore_latest(ctx) is False
            # Same seam, next tier down: Orbax restores step 2 (w=3).
            orbax_cb = OrbaxCheckpointCallback(
                orbax_dir, every=3 if rank == 0 else 0
            )
            assert orbax_cb.restore_latest(ctx) is True
            assert ctx.start_step == 3
            np.testing.assert_array_equal(np.asarray(ctx.state["w"]), np.full((4,), 3.0))

            cbs = [local_cb] + ([orbax_cb] if rank == 0 else [])
            ctx = run_training(_step_fn, ctx.state, num_steps=6, callbacks=cbs, ctx=ctx)
            assert float(ctx.state["w"][0]) == 6.0

            # The local tier is repopulated with coverage-complete saves over
            # the rebuilt group: find_latest agrees at 6 and every survivor
            # holds its own shard AND its clique peer's mirror.
            assert mgr.find_latest() == 6
            held = {i.owner for i in mgr.local_ids() if i.iteration == 6}
            assert held == set(survivors), held
            tree, _ = mgr.load_tree(6)
            np.testing.assert_array_equal(np.asarray(tree["w"]), np.full((4,), 6.0))
            orbax_cb.close()
            mgr.close()
        finally:
            ex.close()

    run_ranks(len(survivors), restart_phase, timeout=240.0)
    for s in stores:
        s.close()
