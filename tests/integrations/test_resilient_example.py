"""The full-stack example (launcher + FT heartbeats + straggler sections +
hierarchical checkpoints + injected crash + resume) driven end to end."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def test_resilient_training_example(tmp_path):
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu", TPU_RESILIENCY_LOG_LEVEL="INFO")
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    p = subprocess.Popen(
        [
            sys.executable, "-m", "tpu_resiliency.launcher.launch",
            "--nproc-per-node", "1", "--rdzv-endpoint", "127.0.0.1:0",
            "--max-restarts", "2", "--rdzv-last-call", "0.2",
            "--monitor-interval", "0.1",
            "--ft-param-initial_rank_heartbeat_timeout", "60",
            "--ft-param-rank_heartbeat_timeout", "60",
            "--run-dir", str(tmp_path / "run"),
            os.path.join(REPO, "examples", "resilient_training.py"),
            "--steps", "20", "--ckpt-dir", str(tmp_path / "ckpt"),
        ],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, cwd=str(tmp_path), start_new_session=True,
    )
    try:
        out, err = p.communicate(timeout=300)
    except subprocess.TimeoutExpired:
        # Kill the whole tree: workers run in their own sessions and would
        # otherwise hold the pipe open past the launcher's death.
        import signal

        os.killpg(os.getpgid(p.pid), signal.SIGKILL)
        out, err = p.communicate()
        raise AssertionError(f"launcher wedged:\n{out[-2000:]}\n{err[-2000:]}")
    assert p.returncode == 0, f"{out[-2000:]}\n{err[-2000:]}"
    # Round 1 resumed from the local checkpoint written before the round-0 crash.
    assert "resumed" in out.lower() or "resumed" in err.lower(), (
        out[-1500:], err[-1500:]
    )
