"""Recovery-latency harness sanity (scripts/bench_restart.py): both restart layers
measure, and the in-process engine beats a full process respawn."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_restart_latency_harness(tmp_path):
    out = tmp_path / "BENCH_restart.json"
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "scripts", "bench_restart.py"),
            "--restarts", "2",
            "--out", str(out),
        ],
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    summary = json.loads(out.read_text())
    inproc = summary["in_process"]["faulting_rank_ms"]["median"]
    injob = summary["in_job"]["respawn_ms"]
    assert 0 < inproc, summary
    assert 0 < injob, summary
    # The entire point of the in-process layer: recovery without interpreter,
    # import, and rendezvous startup. That claim is about environments where
    # interpreter startup actually costs something (a TPU image's plugin boot
    # is seconds); in a featherweight env (measured floor < 1 s — seen when
    # JAX_PLATFORMS=cpu short-circuits the site plugin) a bare respawn can
    # legitimately tie the config-bound engine latency, so only sanity-bound it.
    floor = summary["in_job"]["python_startup_floor_ms"]
    if floor > 1000:
        assert inproc < injob, summary
    else:
        assert inproc < 2000, summary
