"""Recovery-latency harness sanity (scripts/bench_restart.py) plus the
slow-marked perf gates the ISSUE-9 acceptance criteria hang off: warm-path
respawn within 2.5x the in-process restart median, and fast-path rendezvous
at most half the full ladder's median — regressions fail CI, not a JSON
diff."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def test_restart_latency_harness(tmp_path):
    out = tmp_path / "BENCH_restart.json"
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "scripts", "bench_restart.py"),
            "--restarts", "2",
            "--out", str(out),
        ],
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    summary = json.loads(out.read_text())
    inproc = summary["in_process"]["faulting_rank_ms"]["median"]
    injob = summary["in_job"]["respawn_ms"]
    assert 0 < inproc, summary
    assert 0 < injob, summary
    # The decomposition must be present and self-consistent on both in-job
    # legs: segments are non-negative and sum to no more than the total.
    for leg in ("in_job", "in_job_warm_spares"):
        d = summary[leg]
        segs = [d["detect_ms"], d["teardown_ms"], d["rendezvous_ms"]]
        segs.append(
            d["spawn_and_startup_ms"] if "spawn_and_startup_ms" in d
            else d["promote_ms"] + d["first_step_ready_ms"]
        )
        assert all(s >= 0 for s in segs), d
        assert sum(segs) <= d["respawn_ms"] * 1.05 + 1.0, d
    # The warm leg must actually have promoted (else it measured a cold run).
    assert "promote_ms" in summary["in_job_warm_spares"]
    # Structural acceptance: second-restart compile-cache hit recorded.
    assert summary["compile_cache"]["restart_hit"], summary["compile_cache"]
    # The entire point of the in-process layer: recovery without interpreter,
    # import, and rendezvous startup. That claim is about environments where
    # interpreter startup actually costs something (a TPU image's plugin boot
    # is seconds); in a featherweight env (measured floor < 1 s — seen when
    # JAX_PLATFORMS=cpu short-circuits the site plugin) the event-driven
    # in-job respawn can legitimately beat the config-bound engine latency,
    # so only sanity-bound it.
    floor = summary["in_job"]["python_startup_floor_ms"]
    if floor > 1000:
        assert inproc < injob, summary
    else:
        assert inproc < 2000, summary


@pytest.mark.slow
def test_warm_respawn_within_2_5x_of_inprocess():
    """The ISSUE-9 headline gate: warm-path in-job respawn ≤ 2.5× the
    in-process restart median (and ≤ 400 ms absolute on loopback). Best of
    two attempts damps machine-load noise, same policy as the ckpt fg-ratio
    gate."""
    from scripts.bench_restart import bench_injob, bench_inprocess

    inproc = bench_inprocess(2)["faulting_rank_ms"]["median"]
    best = min(
        bench_injob(warm_spares=2)["respawn_ms"] for _ in range(2)
    )
    assert best <= 400.0, f"warm respawn {best:.0f} ms > 400 ms"
    assert best <= 2.5 * inproc, (
        f"warm respawn {best:.0f} ms > 2.5x in-process {inproc:.0f} ms"
    )


@pytest.mark.slow
def test_fastpath_rendezvous_at_most_half_the_ladder():
    """Replacement rounds with unchanged membership must close in ≤ 0.5× the
    full ladder's median (the committed 16-node loopback run shows ~3×)."""
    from scripts.bench_restart import bench_rendezvous_fastpath

    r = bench_rendezvous_fastpath(nodes=16, rounds=8)
    assert r["fast_path_ms"]["median"] <= 0.5 * r["full_ladder_ms"]["median"], r


@pytest.mark.slow
def test_compile_cache_restart_hit_and_cheaper_rejit():
    """Round N+1 must find the persistent compilation cache warm."""
    from scripts.bench_restart import bench_compile_cache

    r = bench_compile_cache()
    assert r["restart_hit"], r
    assert r["outcomes"][0] == "miss", r
    # The re-jit skips XLA compilation; allow generous slack for load noise.
    assert r["restart_jit_ms"] <= r["first_jit_ms"] * 1.5, r
