"""Slow-marked watchtower perf gate, riding the PR-13 store-storm bench:
wiring the alert engine into the live events stream (a ``WatchtowerSink``
processing the store's own ``store_stats`` emissions plus evaluating the full
builtin rule set on its boundaries) must add <5% to the client-observed op
p50 — the regression anchor for the ``--alerts on`` default. Same discipline
as the PR-13 telemetry gate: interleaved median-of-9 trials, one noise-guard
retry."""

import os
import statistics
import sys

import pytest

from tpu_resiliency.telemetry.watchtower import Watchtower, WatchtowerSink
from tpu_resiliency.utils import events

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "scripts"))

import bench_store  # noqa: E402

pytestmark = pytest.mark.slow


def _bench_overhead(trials=9, ops_per_client=4000):
    """Client-observed p50 with the watchtower wired into the events stream
    vs not: interleaved on/off trials (fresh server each, background-load
    spikes hit both arms), compared by MEDIAN. The server emits store_stats
    on a tight cadence so the ON arm's sink genuinely taps and evaluates on
    the storm's emitting thread — the only path the engine could tax."""
    p50 = {True: [], False: []}
    engaged = 0
    for _ in range(trials):
        for on in (True, False):
            srv = bench_store.KVServer(
                host="127.0.0.1", port=0,
                stats_enabled=True, stats_interval=0.05,
            )
            sink = None
            if on:
                tower = Watchtower(
                    eval_interval=0.05, emit=lambda *a: None
                )
                sink = WatchtowerSink(tower)
                events.add_sink(sink)
            try:
                p50[on].append(
                    bench_store.run_storm(srv.port, 1, ops_per_client)["p50_us"]
                )
            finally:
                if sink is not None:
                    events.remove_sink(sink)
                    if (tower.store.query("tpu_store_mean_latency")
                            and tower.status()["clock"]["evals"] > 0):
                        engaged += 1
                srv.close()
    on_p50 = statistics.median(p50[True])
    off_p50 = statistics.median(p50[False])
    return {
        "stats_on_p50_us": round(on_p50, 2),
        "stats_off_p50_us": round(off_p50, 2),
        "overhead_frac": on_p50 / off_p50 - 1.0 if off_p50 else None,
        "engaged_trials": engaged,
        "trials": trials,
    }


def test_watchtower_overhead_under_five_percent():
    res = _bench_overhead()
    # A gate that accidentally benchmarks an idle engine proves nothing: the
    # ON arm must have tapped store_stats AND evaluated rules in most trials.
    assert res["engaged_trials"] >= res["trials"] - 1, res
    if res["overhead_frac"] >= 0.05:
        retry = _bench_overhead()
        assert retry["engaged_trials"] >= retry["trials"] - 1, retry
        res = min((res, retry), key=lambda r: r["overhead_frac"])
    assert res["overhead_frac"] < 0.05, (
        f"watchtower costs {100 * res['overhead_frac']:.1f}% storm p50 "
        f"(on {res['stats_on_p50_us']} us vs off {res['stats_off_p50_us']} us)"
    )
