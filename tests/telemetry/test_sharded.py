"""North-star pipeline tests: mesh-sharded scoring must match the single-program
path bit-for-bit with zero host-side gathers, and the device rings must be
appendable from inside a jitted (donated) train step.

Runs on the 8-virtual-CPU-device mesh from conftest — the sharded path's
collectives (pmin / all_gather) execute for real across devices.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_resiliency.telemetry import scoring
from tpu_resiliency.telemetry.sharded import MeshTelemetry, TelemetryState

R, S, W = 16, 6, 8  # 16 rank rows over 8 devices: 2 rows per shard


@pytest.fixture(scope="module")
def mesh():
    return Mesh(np.array(jax.devices()), ("rank",))


def synth_telemetry(seed=0, slow_ranks=(3, 11), slow_factor=3.0):
    rng = np.random.default_rng(seed)
    data = rng.gamma(4.0, 0.01, size=(R, S, W)).astype(np.float32)
    for r in slow_ranks:
        data[r] *= slow_factor
    counts = np.full((R, S), W, dtype=np.int32)
    # A signal with partial observations and one nobody measured.
    counts[:, S - 2] = rng.integers(1, W, size=R)
    counts[:, S - 1] = 0
    return data, counts


def test_sharded_scores_match_single_program(mesh):
    data, counts = synth_telemetry()
    ewma0 = np.ones(R, np.float32)
    hist0 = np.full((R, S), np.inf, np.float32)

    ref = scoring.score_round_jit(
        jnp.asarray(data), jnp.asarray(counts), jnp.asarray(ewma0), jnp.asarray(hist0)
    )

    shard = NamedSharding(mesh, P("rank"))
    args = [
        jax.device_put(jnp.asarray(x), shard) for x in (data, counts, ewma0, hist0)
    ]
    got = scoring.score_round_sharded(*args, mesh=mesh, axis="rank")

    for name in ("section_scores", "individual_section_scores", "perf", "z", "ewma",
                 "straggler", "historical_min"):
        a = np.asarray(getattr(ref, name))
        b = np.asarray(getattr(got, name))
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7, err_msg=name)
    # Outputs stay sharded over the mesh — no implicit full replication.
    assert not got.perf.sharding.is_fully_replicated
    # The injected slow ranks are flagged.
    assert set(np.nonzero(np.asarray(got.straggler))[0]) >= {3, 11}


def test_mesh_telemetry_round_trip(mesh):
    mt = MeshTelemetry(
        mesh, "rank", n_ranks=R, signal_names=tuple(f"s{i}" for i in range(S)),
        window=W,
    )
    state = mt.init_state()
    assert not state.data.sharding.is_fully_replicated

    rng = np.random.default_rng(1)
    # Homogeneous fleet: same per-signal baseline, ±2% per-rank jitter.
    base = np.tile(rng.gamma(4.0, 0.01, size=(1, S)), (R, 1)).astype(np.float32)
    base *= 1.0 + rng.uniform(-0.02, 0.02, size=(R, S)).astype(np.float32)
    for i in range(W + 3):  # overfill: ring must wrap
        values = base * (1.0 + 0.01 * i)
        values[5] *= 4.0  # rank 5 is slow every step
        state = mt.push(state, jnp.asarray(values))
    assert int(np.asarray(state.counts).max()) == W

    state, report = mt.generate_report(state)
    assert report.world_size == R
    assert set(report.perf_scores) == set(range(R))
    stragglers = report.identify_stragglers()
    assert {sid.rank for sid in stragglers.by_perf} == {5}
    # Rings reset, carry preserved.
    assert int(np.asarray(state.counts).sum()) == 0
    assert float(np.asarray(state.ewma)[5]) < float(np.asarray(state.ewma)[0])


def test_push_inside_jitted_step_with_donation(mesh):
    """The intended hot-loop shape: rings are part of the donated step carry."""
    mt = MeshTelemetry(mesh, "rank", n_ranks=R, signal_names=("step",), window=W)
    state = mt.init_state()
    shard = NamedSharding(mesh, P("rank"))
    x = jax.device_put(jnp.ones((R, 4), jnp.float32), shard)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(tstate: TelemetryState, x):
        y = (x * 2.0).sum(axis=1)  # stand-in compute, [R]
        tstate = MeshTelemetry._push_impl(tstate, y[:, None])
        return tstate, y

    for _ in range(5):
        state, y = step(state, x)
    assert int(np.asarray(state.cursor)) == 5
    np.testing.assert_allclose(np.asarray(state.data)[:5, :, 0], 8.0)


def test_summary_path_matches_ring_path(mesh):
    """score_local_summary (the multi-host Detector bridge) must agree with the
    single-program summary scorer on identical inputs."""
    from tpu_resiliency.telemetry.reporting import ReportGenerator

    data, counts = synth_telemetry(seed=7)
    medians = np.asarray(scoring.masked_median(jnp.asarray(data), jnp.asarray(counts)))
    weights = np.asarray(scoring.masked_total(jnp.asarray(data), jnp.asarray(counts)))

    gen = ReportGenerator(world_size=R, max_signals=S)
    ref = gen.score_summary(
        jnp.asarray(medians), jnp.asarray(weights), jnp.asarray(counts)
    )

    mt = MeshTelemetry(
        mesh, "rank", n_ranks=R, signal_names=tuple(f"s{i}" for i in range(S)),
    )
    got = mt.score_local_summary(medians, weights, counts)  # 1 process = full rows
    for name in ("section_scores", "perf", "z", "ewma", "straggler"):
        np.testing.assert_allclose(
            np.asarray(getattr(ref, name)), np.asarray(getattr(got, name)),
            rtol=1e-5, atol=2e-6, err_msg=name,  # float reduction-order noise
        )
    # Carried summary state is device-resident and sharded.
    assert not mt._summary_state[0].sharding.is_fully_replicated


def test_ewma_carries_across_reports(mesh):
    mt = MeshTelemetry(mesh, "rank", n_ranks=R, signal_names=("a",), window=W,
                       ewma_alpha=0.5)
    state = mt.init_state()
    vals = np.ones((R, 1), np.float32)
    vals[2] = 5.0
    for _ in range(W):
        state = mt.push(state, jnp.asarray(vals))
    state, r1 = mt.generate_report(state)
    for _ in range(W):
        state = mt.push(state, jnp.asarray(vals))
    state, r2 = mt.generate_report(state)
    # Same raw perf both rounds; EWMA converges toward it from 1.0.
    assert r2.ewma_scores[2] < r1.ewma_scores[2] < 1.0
    assert r1.iteration == 1 and r2.iteration == 2
