"""Property-based tests (hypothesis) for the scoring pipeline's invariants and the
native/python ring parity — randomized inputs catch the edge shapes (empty
windows, single samples, ties, wraps) that example-based tests miss."""

import numpy as np
from hypothesis import given, settings, strategies as st

from tpu_resiliency.telemetry import ring_buffer as rb
from tpu_resiliency.telemetry import scoring

settings.register_profile("ci", max_examples=40, deadline=None)
settings.load_profile("ci")


@st.composite
def telemetry_case(draw):
    r = draw(st.integers(2, 12))
    s = draw(st.integers(1, 5))
    w = draw(st.integers(1, 10))
    data = draw(
        st.lists(
            st.floats(np.float32(1e-4), np.float32(1e3), allow_nan=False, allow_subnormal=False, width=32),
            min_size=r * s * w,
            max_size=r * s * w,
        )
    )
    counts = draw(st.lists(st.integers(0, 10), min_size=r * s, max_size=r * s))
    counts = np.minimum(np.asarray(counts, np.int32).reshape(r, s), w)
    return np.asarray(data, np.float32).reshape(r, s, w), counts


@given(telemetry_case())
def test_masked_median_matches_numpy(case):
    import jax.numpy as jnp

    data, counts = case
    got = np.asarray(scoring.masked_median(jnp.asarray(data), jnp.asarray(counts)))
    r, s, _ = data.shape
    for i in range(r):
        for j in range(s):
            n = counts[i, j]
            if n == 0:
                assert got[i, j] == np.inf
            else:
                np.testing.assert_allclose(
                    got[i, j], np.median(data[i, j, :n]), rtol=1e-5
                )


@given(telemetry_case())
def test_score_round_invariants(case):
    import jax.numpy as jnp

    data, counts = case
    r, s, _ = data.shape
    res = scoring.score_round_jit(
        jnp.asarray(data),
        jnp.asarray(counts),
        jnp.ones((r,)),
        jnp.full((r, s), jnp.inf),
    )
    section = np.asarray(res.section_scores)
    perf = np.asarray(res.perf)
    valid = counts > 0
    # Relative scores are min-of-medians / own-median: bounded (0, 1] where valid.
    assert np.all(section[valid] <= 1.0 + 1e-5)
    assert np.all(section[valid] > 0.0)
    # Every signal someone measured has at least one rank at the reference (1.0).
    for j in range(s):
        if valid[:, j].any():
            assert section[valid[:, j], j].max() > 1.0 - 1e-4
    # Perf scores are weighted means of section scores: same bounds.
    has_any = valid.any(axis=1)
    assert np.all(perf[has_any] <= 1.0 + 1e-5)
    assert np.all(perf[has_any] > 0.0)
    assert np.all(np.isfinite(perf))


@given(
    st.integers(1, 24),
    st.lists(st.floats(np.float32(-1e6), np.float32(1e6), allow_nan=False, allow_subnormal=False, width=32), min_size=0, max_size=80),
)
def test_ring_backends_agree(capacity, samples):
    if rb._ringstats is None:
        import pytest

        pytest.skip("_ringstats extension not built")
    nat = rb.HostRingBuffer(capacity, native=True)
    py = rb.HostRingBuffer(capacity, native=False)
    for v in samples:
        nat.push(float(v))
        py.push(float(v))
    assert len(nat) == len(py)
    np.testing.assert_allclose(nat.linearize(), py.linearize())
    if len(py):
        sn, sp = nat.stats(), py.stats()
        for k in sp:
            np.testing.assert_allclose(sn[k], sp[k], rtol=1e-10, atol=1e-9, err_msg=k)


@st.composite
def radix_case(draw):
    """Like telemetry_case but the window size also samples the LARGE regime
    (past the quadratic cap) where auto_mode actually selects radix."""
    r = draw(st.integers(2, 6))
    s = draw(st.integers(1, 3))
    w = draw(st.one_of(st.integers(1, 10), st.integers(129, 260)))
    data = draw(
        st.lists(
            st.floats(np.float32(1e-4), np.float32(1e3), allow_nan=False, allow_subnormal=False, width=32),
            min_size=r * s * w,
            max_size=r * s * w,
        )
    )
    counts = draw(st.lists(st.integers(0, w), min_size=r * s, max_size=r * s))
    return (
        np.asarray(data, np.float32).reshape(r, s, w),
        np.asarray(counts, np.int32).reshape(r, s),
    )


@given(radix_case())
def test_radix_kernel_matches_loop_kernel(case):
    """The O(32*W) radix-select formulation is bit-identical to rank-counting
    on arbitrary windows/counts (ties, empties, single samples, tiny/huge
    magnitudes, and windows past the quadratic cap) — the invariant that lets
    auto-selection switch modes by size without changing results."""
    import jax.numpy as jnp

    from tpu_resiliency.ops.scoring_pallas import fused_median_weights

    data, counts = case
    r = data.shape[0]
    loop = fused_median_weights(
        jnp.asarray(data), jnp.asarray(counts), rank_tile=r, interpret=True,
        mode="loop",
    )
    radix = fused_median_weights(
        jnp.asarray(data), jnp.asarray(counts), rank_tile=r, interpret=True,
        mode="radix",
    )
    # Bit-identical, weights included: both kernels share the masked-sum
    # expression today, and a divergence introduced by a future edit must not
    # hide behind a tolerance (mode auto-switching relies on identity).
    np.testing.assert_array_equal(np.asarray(loop[0]), np.asarray(radix[0]))
    np.testing.assert_array_equal(np.asarray(loop[1]), np.asarray(radix[1]))
