import numpy as np
import pytest

from tpu_resiliency.telemetry import DeviceRings, HostRingBuffer, NameRegistry


def test_host_ring_wraps():
    rb = HostRingBuffer(4)
    for v in range(6):
        rb.push(float(v))
    assert len(rb) == 4
    np.testing.assert_array_equal(rb.linearize(), [2.0, 3.0, 4.0, 5.0])
    rb.reset()
    assert len(rb) == 0
    rb.push(9.0)
    np.testing.assert_array_equal(rb.linearize(), [9.0])


def test_host_ring_partial():
    rb = HostRingBuffer(8)
    rb.extend([1, 2, 3])
    np.testing.assert_array_equal(rb.linearize(), [1.0, 2.0, 3.0])


def test_device_rings_push_inside_jit():
    import jax
    import jax.numpy as jnp

    rings = DeviceRings.create(n_signals=3, capacity=4)

    @jax.jit
    def step(r, vals):
        return r.push_row(vals)

    for i in range(6):
        rings = step(rings, jnp.asarray([i, 10 + i, 100 + i], jnp.float32))
    assert int(rings.cursor) == 6
    np.testing.assert_array_equal(np.asarray(rings.counts), [4, 4, 4])
    # signal 0 holds last 4 values in ring order [4, 5, 2, 3]
    assert set(np.asarray(rings.data)[0].tolist()) == {2.0, 3.0, 4.0, 5.0}
    mask = np.asarray(rings.valid_mask())
    assert mask.all()


def test_device_rings_valid_mask_partial():
    import jax.numpy as jnp

    rings = DeviceRings.create(n_signals=2, capacity=4)
    rings = rings.push_row(jnp.asarray([1.0, 2.0]))
    mask = np.asarray(rings.valid_mask())
    np.testing.assert_array_equal(mask.sum(axis=1), [1, 1])


def test_name_registry():
    reg = NameRegistry(3)
    assert reg.get("a") == 0
    assert reg.get("b") == 1
    assert reg.get("a") == 0
    assert reg.names() == ("a", "b")
    reg.get("c")
    with pytest.raises(ValueError):
        reg.get("d")


def test_name_registry_store_sync(coord_store):
    r0 = NameRegistry(8)
    r1 = NameRegistry(8)
    r0.get("x")
    r1.get("y")
    # publish-all then merge-all (the barrier-separated pattern the Detector uses)
    r0.publish(coord_store)
    r1.publish(coord_store)
    r0.merge(coord_store)
    r1.merge(coord_store)
    assert r0.index_map() == {"x": 0, "y": 1}
    assert r1.index_map() == {"y": 0, "x": 1}
    # convergence: next round both publish their full sets and agree on membership
    assert set(r0.index_map()) == set(r1.index_map())


class TestNativeParity:
    """The native collector (native/ringstats.c) and the Python fallback must be
    interchangeable: same linearize, same stats, same wrap semantics."""

    def _pair(self, capacity):
        import pytest

        from tpu_resiliency.telemetry import ring_buffer as rb

        if rb._ringstats is None:
            pytest.skip("_ringstats extension not built")
        return (
            rb.HostRingBuffer(capacity, native=True),
            rb.HostRingBuffer(capacity, native=False),
        )

    def test_stats_and_linearize_parity(self):
        import numpy as np

        nat, py = self._pair(16)
        assert nat.native and not py.native
        rng = np.random.default_rng(0)
        samples = rng.uniform(0.001, 0.1, 37)  # > 2x capacity: wraps twice
        for v in samples:
            nat.push(float(v))
            py.push(float(v))
        assert len(nat) == len(py) == 16
        np.testing.assert_allclose(nat.linearize(), py.linearize())
        sn, sp = nat.stats(), py.stats()
        assert set(sn) == set(sp)
        for k in sn:
            np.testing.assert_allclose(sn[k], sp[k], rtol=1e-12, err_msg=k)

    def test_extend_reset_parity(self):
        import numpy as np
        import pytest

        nat, py = self._pair(8)
        nat.extend([1.0, 2.0, 3.0])
        py.extend([1.0, 2.0, 3.0])
        np.testing.assert_allclose(nat.linearize(), py.linearize())
        assert nat.stats()["median"] == py.stats()["median"] == 2.0
        nat.reset()
        py.reset()
        assert len(nat) == len(py) == 0
        for ring in (nat, py):
            with pytest.raises(ValueError):
                ring.stats()

    def test_even_count_median(self):
        nat, py = self._pair(8)
        for v in (1.0, 2.0, 3.0, 10.0):
            nat.push(v)
            py.push(v)
        assert nat.stats()["median"] == py.stats()["median"] == 2.5
