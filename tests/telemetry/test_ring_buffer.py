import numpy as np
import pytest

from tpu_resiliency.telemetry import DeviceRings, HostRingBuffer, NameRegistry


def test_host_ring_wraps():
    rb = HostRingBuffer(4)
    for v in range(6):
        rb.push(float(v))
    assert len(rb) == 4
    np.testing.assert_array_equal(rb.linearize(), [2.0, 3.0, 4.0, 5.0])
    rb.reset()
    assert len(rb) == 0
    rb.push(9.0)
    np.testing.assert_array_equal(rb.linearize(), [9.0])


def test_host_ring_partial():
    rb = HostRingBuffer(8)
    rb.extend([1, 2, 3])
    np.testing.assert_array_equal(rb.linearize(), [1.0, 2.0, 3.0])


def test_device_rings_push_inside_jit():
    import jax
    import jax.numpy as jnp

    rings = DeviceRings.create(n_signals=3, capacity=4)

    @jax.jit
    def step(r, vals):
        return r.push_row(vals)

    for i in range(6):
        rings = step(rings, jnp.asarray([i, 10 + i, 100 + i], jnp.float32))
    assert int(rings.cursor) == 6
    np.testing.assert_array_equal(np.asarray(rings.counts), [4, 4, 4])
    # signal 0 holds last 4 values in ring order [4, 5, 2, 3]
    assert set(np.asarray(rings.data)[0].tolist()) == {2.0, 3.0, 4.0, 5.0}
    mask = np.asarray(rings.valid_mask())
    assert mask.all()


def test_device_rings_valid_mask_partial():
    import jax.numpy as jnp

    rings = DeviceRings.create(n_signals=2, capacity=4)
    rings = rings.push_row(jnp.asarray([1.0, 2.0]))
    mask = np.asarray(rings.valid_mask())
    np.testing.assert_array_equal(mask.sum(axis=1), [1, 1])


def test_name_registry():
    reg = NameRegistry(3)
    assert reg.get("a") == 0
    assert reg.get("b") == 1
    assert reg.get("a") == 0
    assert reg.names() == ("a", "b")
    reg.get("c")
    with pytest.raises(ValueError):
        reg.get("d")


def test_name_registry_store_sync(coord_store):
    r0 = NameRegistry(8)
    r1 = NameRegistry(8)
    r0.get("x")
    r1.get("y")
    # publish-all then merge-all (the barrier-separated pattern the Detector uses)
    r0.publish(coord_store)
    r1.publish(coord_store)
    r0.merge(coord_store)
    r1.merge(coord_store)
    assert r0.index_map() == {"x": 0, "y": 1}
    assert r1.index_map() == {"y": 0, "x": 1}
    # convergence: next round both publish their full sets and agree on membership
    assert set(r0.index_map()) == set(r1.index_map())
