"""telemetry/watchtower.py: rule engine, stream clock, replay determinism."""

import json

import pytest

from tpu_resiliency.utils import events
from tpu_resiliency.utils.events import Event
from tpu_resiliency.utils.metrics import MetricsRegistry, observe_record
from tpu_resiliency.telemetry.watchtower import (
    ALERT_RULES_ENV,
    AlertRule,
    Watchtower,
    WatchtowerSink,
    default_rules,
    load_rule_overrides,
    replay,
)


@pytest.fixture(autouse=True)
def clean_sinks():
    events.clear_sinks()
    yield
    events.clear_sinks()


def _hot_rule(threshold=10.0, **kw):
    """Fires while any retained tpu_goodput_ratio sample >= threshold."""
    def check(store, now, p):
        if any(v >= p["threshold"] for _, v in store.query("tpu_goodput_ratio")):
            return f"hot (>= {p['threshold']:g})"
        return None
    return AlertRule(
        name=kw.pop("name", "hot"), check=check,
        params={"threshold": threshold}, **kw,
    )


def _gp(ts, ratio):
    return {"ts": ts, "kind": "goodput_update", "ratio": ratio}


def _steps(t0, n, step_s, pid=1, start_it=0):
    recs, t = [], t0
    for i in range(n):
        t += step_s
        recs.append({"ts": t, "kind": "iteration_start",
                     "iteration": start_it + i, "pid": pid})
    return recs


class TestStreamClock:
    def test_boundary_evaluated_before_ingesting_crossing_record(self):
        # The record that crosses a boundary must NOT be visible to that
        # boundary's evaluation — ring contents at each boundary are a pure
        # function of record order (the replay-parity invariant).
        tower = Watchtower([_hot_rule()], eval_interval=5.0, emit=lambda *a: None)
        tower.observe(_gp(0.0, 0.0))       # clock starts: next eval at 5.0
        trs = tower.observe(_gp(5.0, 99.0))  # crosses; evaluated BEFORE ingest
        assert trs == []
        trs = tower.observe(_gp(10.0, 0.0))  # now the 99 sample is visible
        assert [t["kind"] for t in trs] == ["alert_fired"]
        assert trs[0]["fire_ts"] == 10.0

    def test_hold_down_for_s(self):
        tower = Watchtower(
            [_hot_rule(for_s=10.0)], eval_interval=5.0, emit=lambda *a: None
        )
        out = tower.observe_many(
            [_gp(0.0, 99.0), _gp(5.0, 99.0), _gp(10.0, 99.0), _gp(15.0, 99.0),
             _gp(20.0, 99.0)]
        )
        fires = [t for t in out if t["kind"] == "alert_fired"]
        # pending since the 5.0 boundary; 10s hold-down met at the 15.0 one.
        assert len(fires) == 1 and fires[0]["fire_ts"] == 15.0

    def test_resolve_carries_duration(self):
        tower = Watchtower([_hot_rule()], eval_interval=5.0, emit=lambda *a: None)
        tower.observe_many([_gp(0.0, 99.0), _gp(5.0, 99.0)])
        # cool samples push the hot one out of the 4-slot ring
        cool = [_gp(10.0 + i, 0.0) for i in range(600)]
        out = tower.observe_many(cool)
        resolved = [t for t in out if t["kind"] == "alert_resolved"]
        assert len(resolved) == 1
        r = resolved[0]
        assert r["resolve_ts"] > r["fire_ts"]
        assert r["duration_s"] == pytest.approx(r["resolve_ts"] - r["fire_ts"])

    def test_pathological_gap_snaps_clock(self):
        tower = Watchtower([_hot_rule()], eval_interval=5.0, emit=lambda *a: None)
        tower.observe(_gp(0.0, 0.0))
        tower.observe(_gp(1e6, 0.0))  # ~200k boundaries: snap, don't loop
        st = tower.status()
        assert st["clock"]["evals"] == 256
        assert st["clock"]["next_eval"] == 1e6 + 5.0

    def test_non_dict_and_tsless_records_ignored(self):
        tower = Watchtower([_hot_rule()], emit=lambda *a: None)
        assert tower.observe("nope") == []
        assert tower.observe({"kind": "iteration_start"}) == []
        assert tower.status()["clock"]["hwm"] is None


class TestRules:
    def test_crashing_rule_degrades_to_error_row(self):
        def boom(store, now, p):
            raise RuntimeError("rule bug")

        tower = Watchtower(
            [AlertRule(name="boom", check=boom), _hot_rule()],
            eval_interval=5.0, emit=lambda *a: None,
        )
        out = tower.observe_many([_gp(0.0, 99.0), _gp(5.0, 99.0), _gp(10.0, 99.0)])
        # the healthy rule still fires; the crasher reports, never raises
        assert any(t["rule"] == "hot" for t in out)
        rows = {r["name"]: r for r in tower.status()["rules"]}
        assert "rule bug" in rows["boom"]["error"]
        assert rows["hot"]["error"] is None

    def test_active_alerts_severity_ranked(self):
        tower = Watchtower(
            [_hot_rule(name="w", severity="warn"),
             _hot_rule(name="p", severity="page"),
             _hot_rule(name="i", severity="info")],
            eval_interval=5.0, emit=lambda *a: None,
        )
        tower.observe_many([_gp(0.0, 99.0), _gp(5.0, 99.0)])
        assert [a["rule"] for a in tower.active_alerts()] == ["p", "w", "i"]

    def test_builtin_step_anomaly_fires_on_straggler(self):
        rules = [r for r in default_rules() if r.name == "step_anomaly"]
        recs = _steps(0.0, 12, 0.1) + _steps(1.2, 6, 3.0, start_it=12)
        _, seq = replay(recs, rules=rules)
        assert [s["rule"] for s in seq if s["kind"] == "alert_fired"] \
            == ["step_anomaly"]

    def test_builtin_goodput_burn_fast_and_slow(self):
        rules = [r for r in default_rules() if r.name == "goodput_burn"]
        recs = [_gp(2.0 * i, 0.2) for i in range(40)]
        _, seq = replay(recs, rules=rules)
        assert any(s["rule"] == "goodput_burn" and s["kind"] == "alert_fired"
                   for s in seq)
        # a blip burns the fast window only: no page
        recs = [_gp(2.0 * i, 1.0) for i in range(300)] + \
            [_gp(600.0 + 2.0 * i, 0.2) for i in range(3)] + \
            [_gp(606.0 + 2.0 * i, 1.0) for i in range(30)]
        _, seq = replay(recs, rules=rules)
        assert seq == []


class TestReplayParity:
    def _campaign(self):
        recs = _steps(0.0, 12, 0.1) + _steps(1.2, 4, 3.0, start_it=12)
        recs += [_gp(20.0 + 2 * i, 0.2) for i in range(40)]
        recs += [_gp(100.0 + 2 * i, 1.0) for i in range(40)]
        return recs

    def test_same_stream_same_sequence(self):
        r1 = replay(self._campaign(), rules=default_rules())[1]
        r2 = replay(self._campaign(), rules=default_rules())[1]
        assert r1 and [json.dumps(t, sort_keys=True) for t in r1] \
            == [json.dumps(t, sort_keys=True) for t in r2]

    def test_recorded_alert_events_are_inert_on_replay(self):
        recs = self._campaign()
        _, seq = replay(recs, rules=default_rules())
        # splice the emitted transitions back into the stream, as a live
        # run's events tail would see its own alert records
        enriched = sorted(
            recs + [
                {"ts": t.get("resolve_ts") or t["fire_ts"],
                 "source": "watchtower", **t}
                for t in seq
            ],
            key=lambda r: r["ts"],
        )
        _, seq2 = replay(enriched, rules=default_rules())
        assert [json.dumps(t, sort_keys=True) for t in seq] \
            == [json.dumps(t, sort_keys=True) for t in seq2]


class TestTaps:
    def test_step_histogram_tap(self):
        tower = Watchtower([], emit=lambda *a: None)
        tower.observe_many(_steps(0.0, 3, 0.5))
        s = tower.store.query("tpu_step_seconds")
        assert len(s) == 2  # consecutive deltas only
        assert all(v == pytest.approx(0.5) for _, v in s)

    def test_gauges_sample_from_record_not_wall_clock(self):
        tower = Watchtower([], emit=lambda *a: None)
        tower.observe(_gp(123.0, 0.75))
        tower.observe({"ts": 124.0, "kind": "byteflow_update",
                       "accounted_ratio": 0.93, "flows": {}})
        assert tower.store.query("tpu_goodput_ratio") == [(123.0, 0.75)]
        assert tower.store.query("tpu_byteflow_accounted_ratio") \
            == [(124.0, 0.93)]

    def test_ckpt_counter_tap(self):
        tower = Watchtower([], emit=lambda *a: None)
        tower.observe({"ts": 10.0, "kind": "ckpt_saved", "iteration": 1,
                       "nbytes": 100, "duration_s": 0.1})
        tower.observe({"ts": 20.0, "kind": "ckpt_saved", "iteration": 2,
                       "nbytes": 100, "duration_s": 0.1})
        assert tower.store.query("tpu_ckpt_saves") == [(10.0, 1.0), (20.0, 2.0)]

    def test_store_stats_mean_latency_tap(self):
        tower = Watchtower([], emit=lambda *a: None)
        tower.observe({"ts": 5.0, "kind": "store_stats",
                       "ops": {"get": 10}, "op_seconds": {"get": 0.1}})
        tower.observe({"ts": 10.0, "kind": "store_stats",
                       "ops": {"get": 10}, "op_seconds": {"get": 1.0}})
        s = tower.store.query("tpu_store_mean_latency")
        assert [t for t, _ in s] == [5.0, 10.0]
        assert s[0][1] == pytest.approx(0.01)
        assert s[1][1] == pytest.approx(0.1)


class TestConfig:
    def test_env_overrides(self, tmp_path, monkeypatch):
        cfg = tmp_path / "rules.json"
        cfg.write_text(json.dumps({
            "goodput_burn": {"severity": "warn", "for_s": 7.5, "slo": 0.5,
                             "not_a_param": 1},
            "step_anomaly": {"disabled": True},
            "unknown_rule": {"severity": "page"},
        }))
        monkeypatch.setenv(ALERT_RULES_ENV, str(cfg))
        overrides, err = load_rule_overrides()
        assert err is None
        rules = {r.name: r for r in default_rules(overrides)}
        assert "step_anomaly" not in rules
        gb = rules["goodput_burn"]
        assert (gb.severity, gb.for_s) == ("warn", 7.5)
        assert gb.params["slo"] == 0.5
        assert "not_a_param" not in gb.params

    def test_bad_override_file_surfaces_config_error(self, tmp_path, monkeypatch):
        cfg = tmp_path / "rules.json"
        cfg.write_text("{not json")
        monkeypatch.setenv(ALERT_RULES_ENV, str(cfg))
        tower = Watchtower(emit=lambda *a: None)
        assert tower.config_error and str(cfg) in tower.config_error
        # built-ins still loaded — bad config must not disable alerting
        assert {r.name for r in tower.rules} >= {"goodput_burn", "step_anomaly"}
        assert "config_error" in tower.status()

    def test_no_env_no_error(self, monkeypatch):
        monkeypatch.delenv(ALERT_RULES_ENV, raising=False)
        assert load_rule_overrides() == ({}, None)


class TestBridge:
    def test_emitted_events_drive_alert_metrics(self):
        # The engine's default emit rides the standard events bridge:
        # alert_fired/alert_resolved records map to tpu_alerts_total and
        # the tpu_alerts_active gauge via observe_record.
        tower = Watchtower([_hot_rule(severity="page")], eval_interval=5.0)
        recorded = []
        events.add_sink(
            lambda e: recorded.append(e) if e.source == "watchtower" else None
        )
        tower.observe_many([_gp(0.0, 99.0), _gp(5.0, 99.0)])
        assert [e.kind for e in recorded] == ["alert_fired"]
        reg = MetricsRegistry()
        for e in recorded:
            observe_record(e.to_record(), reg)
        prom = reg.to_prometheus()
        assert 'tpu_alerts_total{rule="hot",severity="page"} 1' in prom
        assert "tpu_alerts_active 1" in prom

    def test_sink_flattening_matches_jsonl_replay(self):
        # WatchtowerSink(Event) and a flat-record feed must produce the same
        # ring contents — the live/post-hoc parity contract.
        via_sink = Watchtower([], emit=lambda *a: None)
        sink = WatchtowerSink(via_sink)
        via_flat = Watchtower([], emit=lambda *a: None)
        for i, t in enumerate((1.0, 2.0, 3.0)):
            e = Event(ts=t, source="inprocess", kind="iteration_start",
                      pid=7, payload={"iteration": i})
            sink(e)
            via_flat.observe(e.to_record())
        assert via_sink.store.query("tpu_step_seconds") \
            == via_flat.store.query("tpu_step_seconds")


def test_start_pumps_poll_fn_and_stop_joins():
    tower = Watchtower([], emit=lambda *a: None)
    import threading

    pumped = threading.Event()
    tower.start(poll_fn=pumped.set, interval=0.01)
    assert pumped.wait(timeout=5.0)
    tower.stop()
    assert tower._thread is None
