"""Per-compiled-program device timing (the CUPTI equivalent): xplane extraction,
the capture-window contract (start/stop/drain/get_stats/reset), and the Detector
integration that turns program times into scored ``prog/...`` signals."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from tpu_resiliency.telemetry.detector import Detector
from tpu_resiliency.telemetry.device_profiler import (
    DeviceTimeProfiler,
    extract_program_times,
    normalize_program_name,
)


# --- xplane extraction on a stub object graph (device-plane case) -------------

@dataclasses.dataclass
class _Ev:
    name: str
    duration_ns: float


@dataclasses.dataclass
class _Line:
    name: str
    events: list


@dataclasses.dataclass
class _Plane:
    name: str
    lines: list


@dataclasses.dataclass
class _PD:
    planes: list


def test_extract_prefers_device_plane():
    pd = _PD(
        planes=[
            _Plane(
                "/device:TPU:0",
                [
                    _Line(
                        "XLA Modules",
                        [
                            _Ev("jit_train_step(123)", 1_500_000.0),
                            _Ev("jit_train_step(123)", 1_600_000.0),
                            _Ev("jit_eval(77)", 400_000.0),
                        ],
                    ),
                    _Line("XLA Ops", [_Ev("%fusion", 1.0)]),  # ignored
                ],
            ),
            _Plane("/host:CPU", [_Line("python", [_Ev("PjitFunction(train_step)", 9e9)])]),
        ]
    )
    times = extract_program_times(pd)
    assert set(times) == {"jit_train_step", "jit_eval"}  # host fallback NOT mixed in
    np.testing.assert_allclose(times["jit_train_step"], [1.5e-3, 1.6e-3])


def test_extract_falls_back_to_host_pjit_events():
    pd = _PD(
        planes=[
            _Plane("/host:CPU", [_Line("python", [
                _Ev("PjitFunction(step)", 2_000_000.0),
                _Ev("$profiler.py:101 start_trace", 1.0),  # non-pjit: ignored
            ])]),
        ]
    )
    times = extract_program_times(pd)
    assert set(times) == {"pjit_step"}
    np.testing.assert_allclose(times["pjit_step"], [2e-3])


def test_normalize_strips_fingerprint():
    assert normalize_program_name("jit_f(18446744073709551615)") == "jit_f"
    assert normalize_program_name("jit_f") == "jit_f"


# --- real capture window (CPU backend: host-fallback signal) ------------------

def test_capture_window_end_to_end(tmp_path):
    prof = DeviceTimeProfiler(trace_root=str(tmp_path))

    @jax.jit
    def work(x):
        return jnp.tanh(x @ x).sum()

    x = jnp.ones((128, 128))
    work(x)  # compile outside the window
    with prof:
        for _ in range(3):
            jax.block_until_ready(work(x))

    fresh = prof.drain()
    assert fresh, "no program samples captured"
    name = next(iter(fresh))
    assert len(fresh[name]) >= 3
    assert all(s > 0 for s in fresh[name])
    assert prof.drain() == {}  # drained

    stats = prof.get_stats()
    st = stats[name]
    assert st["count"] >= 3
    assert st["min"] <= st["med"] <= st["max"]
    prof.reset()
    assert prof.get_stats() == {}
    # The window's trace dir is cleaned up.
    assert list(tmp_path.iterdir()) == []


# --- Detector integration ------------------------------------------------------

def test_program_samples_join_the_scored_matrix():
    Detector.initialize(rank=0, world_size=1, report_time_interval=3600.0)
    try:
        for _ in range(8):
            Detector.record_program_samples(
                {"jit_train_step": [1.0e-3], "jit_eval": [0.5e-3]}
            )
        report = Detector.generate_report()
        assert "prog/jit_train_step" in report.section_names
        assert "prog/jit_eval" in report.section_names
        # Single rank: both programs score 1.0 (their own median is the reference).
        assert report.relative_section_scores["prog/jit_train_step"] == 1.0
    finally:
        Detector.shutdown()
