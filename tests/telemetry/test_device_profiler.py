"""Per-compiled-program device timing (the CUPTI equivalent): xplane extraction,
the capture-window contract (start/stop/drain/get_stats/reset), and the Detector
integration that turns program times into scored ``prog/...`` signals."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from tpu_resiliency.telemetry.detector import Detector
from tpu_resiliency.telemetry.device_profiler import (
    DeviceTimeProfiler,
    extract_program_times,
    normalize_program_name,
)


# --- xplane extraction on a stub object graph (device-plane case) -------------

@dataclasses.dataclass
class _Ev:
    name: str
    duration_ns: float


@dataclasses.dataclass
class _Line:
    name: str
    events: list


@dataclasses.dataclass
class _Plane:
    name: str
    lines: list


@dataclasses.dataclass
class _PD:
    planes: list


def test_extract_prefers_device_plane():
    pd = _PD(
        planes=[
            _Plane(
                "/device:TPU:0",
                [
                    _Line(
                        "XLA Modules",
                        [
                            _Ev("jit_train_step(123)", 1_500_000.0),
                            _Ev("jit_train_step(123)", 1_600_000.0),
                            _Ev("jit_eval(77)", 400_000.0),
                        ],
                    ),
                    _Line("XLA Ops", [_Ev("%fusion", 1.0)]),  # ignored
                ],
            ),
            _Plane("/host:CPU", [_Line("python", [_Ev("PjitFunction(train_step)", 9e9)])]),
        ]
    )
    times = extract_program_times(pd)
    assert set(times) == {"jit_train_step", "jit_eval"}  # host fallback NOT mixed in
    np.testing.assert_allclose(times["jit_train_step"], [1.5e-3, 1.6e-3])


def test_extract_falls_back_to_host_pjit_events():
    pd = _PD(
        planes=[
            _Plane("/host:CPU", [_Line("python", [
                _Ev("PjitFunction(step)", 2_000_000.0),
                _Ev("$profiler.py:101 start_trace", 1.0),  # non-pjit: ignored
            ])]),
        ]
    )
    times = extract_program_times(pd)
    assert set(times) == {"pjit_step"}
    np.testing.assert_allclose(times["pjit_step"], [2e-3])


def test_normalize_strips_fingerprint():
    assert normalize_program_name("jit_f(18446744073709551615)") == "jit_f"
    assert normalize_program_name("jit_f") == "jit_f"


# --- real capture window (CPU backend: host-fallback signal) ------------------

def test_capture_window_end_to_end(tmp_path):
    prof = DeviceTimeProfiler(trace_root=str(tmp_path))

    @jax.jit
    def work(x):
        return jnp.tanh(x @ x).sum()

    x = jnp.ones((128, 128))
    work(x)  # compile outside the window
    with prof:
        for _ in range(3):
            jax.block_until_ready(work(x))

    fresh = prof.drain()
    assert fresh, "no program samples captured"
    name = next(iter(fresh))
    assert len(fresh[name]) >= 3
    assert all(s > 0 for s in fresh[name])
    assert prof.drain() == {}  # drained

    stats = prof.get_stats()
    st = stats[name]
    assert st["count"] >= 3
    assert st["min"] <= st["med"] <= st["max"]
    prof.reset()
    assert prof.get_stats() == {}
    # The window's trace dir is cleaned up.
    assert list(tmp_path.iterdir()) == []


# --- Detector integration ------------------------------------------------------

def test_program_samples_join_the_scored_matrix():
    Detector.initialize(rank=0, world_size=1, report_time_interval=3600.0)
    try:
        for _ in range(8):
            Detector.record_program_samples(
                {"jit_train_step": [1.0e-3], "jit_eval": [0.5e-3]}
            )
        report = Detector.generate_report()
        assert "prog/jit_train_step" in report.section_names
        assert "prog/jit_eval" in report.section_names
        # Single rank: both programs score 1.0 (their own median is the reference).
        assert report.relative_section_scores["prog/jit_train_step"] == 1.0
    finally:
        Detector.shutdown()


# --- per-op/scope granularity (the per-kernel-stream analogue) ----------------

def test_op_scope_key_mapping():
    """The pure event→key mapping both plane layouts share: tf_op scope paths
    win (jit wrappers dropped, trailing op dropped), hlo_op/event names fall
    back with compile-order instruction ids stripped, bookkeeping dies."""
    from tpu_resiliency.telemetry.device_profiler import op_scope_key

    # tf_op scope attribution (TPU "XLA Ops" events).
    assert op_scope_key("%fusion.3", {"tf_op": "jit(step)/attn/dot_general"}) == "attn"
    assert (
        op_scope_key("%fusion.9", {"tf_op": "jit(step)/decoder/mlp/dot_general"})
        == "decoder/mlp"
    )
    # Unscoped op: keys by its own de-numbered base name.
    assert op_scope_key("%reduce.1", {"tf_op": "jit(step)/reduce.1"}) == "reduce"
    assert op_scope_key("x", {"tf_op": "jit(step)"}) is None
    # hlo_op fallback (CPU client line events).
    assert op_scope_key("dot_general.2", {"hlo_op": "dot_general.2"}) == "dot_general"
    assert op_scope_key("wrapped_tanh", {}) == "wrapped_tanh"
    # Bookkeeping events are dropped.
    assert op_scope_key("end: dot_general.2", {}) is None
    assert op_scope_key("ThreadpoolListener::StartRegion", {}) is None


def test_extract_op_times_prefers_device_ops_line():
    from tpu_resiliency.telemetry.device_profiler import extract_op_times

    @dataclasses.dataclass
    class _EvS:
        name: str
        duration_ns: float
        stats: list

    pd = _PD(
        planes=[
            _Plane(
                "/device:TPU:0",
                [
                    _Line("XLA Modules", [_Ev("jit_step(1)", 9e9)]),  # not ops
                    _Line(
                        "XLA Ops",
                        [
                            _EvS("%fusion.3", 1_000_000.0, [("tf_op", "jit(step)/attn/dot_general")]),
                            _EvS("%fusion.3", 1_200_000.0, [("tf_op", "jit(step)/attn/dot_general")]),
                            _EvS("%copy.1", 50_000.0, [("tf_op", "jit(step)/mlp/copy")]),
                        ],
                    ),
                ],
            ),
            # Host client line must NOT be mixed in when a device ops line exists.
            _Plane(
                "/host:CPU",
                [_Line("tf_XLAPjRtCpuClient/1", [_EvS("dot_general.2", 7e9, [])])],
            ),
        ]
    )
    times = extract_op_times(pd)
    assert set(times) == {"attn", "mlp"}
    np.testing.assert_allclose(times["attn"], [1e-3, 1.2e-3])


def test_op_capture_window_end_to_end(tmp_path):
    """collect_ops=True on a real CPU trace: the PjRt client per-op line feeds
    op/scope rings through the same window contract (drain_ops/get_op_stats),
    and the Detector turns them into scored op/... signals."""
    prof = DeviceTimeProfiler(trace_root=str(tmp_path), collect_ops=True)

    @jax.jit
    def work(x):
        return jnp.tanh(x @ x).sum()

    x = jnp.ones((128, 128))
    work(x)  # compile outside the window
    with prof:
        for _ in range(3):
            jax.block_until_ready(work(x))

    progs = prof.drain()
    assert progs, "program samples must still be captured alongside ops"
    ops = prof.drain_ops()
    assert ops, "no op samples captured from the client per-op line"
    assert all(all(s > 0 for s in v) for v in ops.values())
    # The matmul appears under its de-numbered hlo base name on CPU.
    assert any("dot" in k for k in ops), sorted(ops)
    assert prof.drain_ops() == {}
    st = prof.get_op_stats()
    k = next(iter(st))
    assert st[k]["count"] >= 1 and st[k]["min"] <= st[k]["max"]

    Detector.initialize(rank=0, world_size=1, report_time_interval=3600.0)
    try:
        Detector.record_op_samples({k: [1.0e-3, 1.1e-3]})
        report = Detector.generate_report()
        assert f"op/{k}" in report.section_names
    finally:
        Detector.shutdown()
    prof.reset()
    assert prof.get_op_stats() == {}
