import time

import numpy as np
import pytest

from tpu_resiliency.exceptions import ResiliencyError
from tpu_resiliency.telemetry import CallableId, Detector


@pytest.fixture(autouse=True)
def clean_detector():
    if Detector.initialized:
        Detector.shutdown()
    yield
    if Detector.initialized:
        Detector.shutdown()


def test_requires_initialize():
    with pytest.raises(ResiliencyError):
        with Detector.detection_section("x"):
            pass


def test_double_initialize_rejected():
    Detector.initialize()
    with pytest.raises(ResiliencyError):
        Detector.initialize()


def test_section_timing_and_report():
    Detector.initialize(report_time_interval=1e9)
    for _ in range(8):
        with Detector.detection_section("step", profile_device=False):
            time.sleep(0.002)
    summary = Detector.local_summary()
    assert "sec/step" in summary
    assert summary["sec/step"]["count"] == 8
    assert summary["sec/step"]["median"] >= 0.002
    report = Detector.generate_report()
    assert report is not None
    assert report.section_names == ("sec/step",)
    # single rank: relative score is 1.0 (it IS the reference)
    assert report.relative_section_scores["sec/step"] == pytest.approx(1.0)
    assert not report.identify_stragglers().any


def test_section_observe_device_timing():
    import jax.numpy as jnp

    Detector.initialize(profiling_interval=2)
    for i in range(4):
        with Detector.detection_section("jitted") as sec:
            sec.observe(jnp.ones((4, 4)) * i)
    summary = Detector.local_summary()
    assert summary["sec/jitted"]["count"] == 4
    # entries 0 and 2 profiled device time
    assert summary["dev/jitted"]["count"] == 2


def test_wrap_callables():
    import jax
    import jax.numpy as jnp

    class Trainer:
        def training_step(self, x):
            return jnp.sum(x * 2.0)

    trainer = Trainer()
    Detector.initialize()
    Detector.wrap_callables([CallableId(trainer, "training_step")])
    for _ in range(3):
        out = trainer.training_step(jnp.ones(8))
        assert float(out) == 16.0
    summary = Detector.local_summary()
    assert summary["sec/Trainer.training_step"]["count"] == 3
    assert summary["dev/Trainer.training_step"]["count"] == 3
    Detector.shutdown()
    # unwrapped after shutdown
    assert not hasattr(trainer.training_step, "__wrapped__")


def test_report_interval(monkeypatch):
    Detector.initialize(report_time_interval=0.0)  # report every iteration once locked
    from tpu_resiliency.telemetry import detector as det_mod

    # lock the tracker immediately
    Detector._interval_tracker.interval = 2
    with Detector.detection_section("s", profile_device=False):
        pass
    assert Detector.generate_report_if_interval_elapsed() is None  # iter 1
    assert Detector.generate_report_if_interval_elapsed() is not None  # iter 2


def test_multirank_aggregation_via_store(kv_server):
    """Three simulated ranks publish summaries; rank 0 scores globally."""
    import threading

    from tpu_resiliency.platform.store import CoordStore

    world = 3
    reports = {}

    def run_rank(rank):
        store = CoordStore("127.0.0.1", kv_server.port)
        # simulate per-rank Detector state without the singleton (store path unit)
        from tpu_resiliency.telemetry.detector import Detector as D

        local = {"sec/step": {"median": 0.1 * (4 if rank == 1 else 1), "total": 1.0, "count": 10}}
        ns = "telemetry/round/0"
        store.set_add(f"{ns}/names", ["sec/step"])
        store.set(f"{ns}/summary/{rank}", local)
        store.barrier(f"{ns}/publish", rank, world, 30.0)
        if rank == 0:
            import jax.numpy as jnp

            from tpu_resiliency.telemetry.reporting import ReportGenerator

            summaries = [store.get(f"{ns}/summary/{r}", timeout=30.0) for r in range(world)]
            medians = np.array([[s["sec/step"]["median"]] for s in summaries], np.float32)
            weights = np.array([[s["sec/step"]["total"]] for s in summaries], np.float32)
            counts = np.array([[s["sec/step"]["count"]] for s in summaries], np.int32)
            gen = ReportGenerator(world_size=world, max_signals=4)
            reports[0] = gen.generate_summary_report(
                jnp.asarray(medians), jnp.asarray(weights), jnp.asarray(counts),
                ("sec/step",), rank=0,
            )
        store.close()

    threads = [threading.Thread(target=run_rank, args=(r,)) for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30.0)
    report = reports[0]
    stragglers = report.identify_stragglers()
    assert {s.rank for s in stragglers.by_perf} == {1}
    assert report.perf_scores[1] == pytest.approx(0.25, abs=0.01)
