"""The north-star configuration, tested for real: 2 JAX processes × 4 CPU devices,
``Detector`` reports riding the mesh (``_generate_mesh_report`` →
``MeshTelemetry.score_local_summary``) across genuine process boundaries.

This is the one configuration the sharded telemetry path exists for: each process
contributes its own summary rows as *shards* of a global mesh array
(``jax.make_array_from_process_local_data``), cross-rank reductions run as XLA
collectives inside the compiled scoring program, and the coordination store carries
only the column-name agreement — **zero per-rank summary traffic** (asserted below
against the store's key space).

Mirrors the reference's multi-process Gloo-on-CPU scoring tests
(``tests/straggler/unit/_utils.py:42-80``) at the JAX process level.
"""

import json
import os
import socket
import subprocess
import sys
import textwrap

import pytest

from tpu_resiliency.platform.store import KVServer

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


CHILD = textwrap.dedent(
    """
    import json, os, sys, time

    rank = int(sys.argv[1])
    kv_port = int(sys.argv[2])
    coord_port = int(sys.argv[3])

    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        f"127.0.0.1:{coord_port}", num_processes=2, process_id=rank
    )
    assert jax.process_count() == 2

    import numpy as np
    from jax.sharding import Mesh

    from tpu_resiliency.platform.store import CoordStore
    from tpu_resiliency.telemetry.detector import Detector
    from tpu_resiliency.telemetry.sharded import MeshTelemetry

    # One telemetry row per Detector rank: a 2-device mesh, one device per process.
    per_proc = [[d for d in jax.devices() if d.process_index == p][0] for p in range(2)]
    mesh = Mesh(np.array(per_proc), ("ranks",))
    mt = MeshTelemetry(
        mesh, "ranks", n_ranks=2, signal_names=tuple(f"c{i}" for i in range(8))
    )

    store = CoordStore("127.0.0.1", kv_port)
    Detector.initialize(
        rank=rank,
        world_size=2,
        store=store,
        gather_on_rank0=False,
        report_time_interval=3600.0,
        device_telemetry=mt,
    )

    # Rank 1 is ~4x slower in the 'step' section; both ranks also time 'io'.
    for _ in range(6):
        with Detector.detection_section("step", profile_device=False):
            time.sleep(0.02 if rank == 1 else 0.005)
        with Detector.detection_section("io", profile_device=False):
            time.sleep(0.004)

    report = Detector.generate_report()
    assert report is not None

    # The mesh path must leave the per-rank summary namespace untouched: the store
    # carried column names only (plus the registry's own bookkeeping).
    leaked = store.prefix_get("telemetry/round/")
    assert leaked == {}, f"summary gather leaked through the store: {leaked}"

    stragglers = report.identify_stragglers(perf_threshold=0.75)
    out = {
        "rank": rank,
        "perf": {str(k): v for k, v in report.perf_scores.items()},
        "by_perf": sorted(s.rank for s in stragglers.by_perf),
        "sections": list(report.section_names),
        "rel_step": report.relative_section_scores.get("sec/step"),
    }

    # Second round: the column agreement is already settled; scores must keep
    # flowing through the same compiled program (EWMA carries across reports).
    for _ in range(4):
        with Detector.detection_section("step", profile_device=False):
            time.sleep(0.02 if rank == 1 else 0.005)
    report2 = Detector.generate_report()
    assert report2 is not None
    assert store.prefix_get("telemetry/round/") == {}
    out["perf2"] = {str(k): v for k, v in report2.perf_scores.items()}

    Detector.shutdown()
    print("RESULT " + json.dumps(out), flush=True)
    """
)


def test_mesh_report_across_process_boundaries(tmp_path):
    script = tmp_path / "child.py"
    script.write_text(CHILD)
    kv = KVServer(host="127.0.0.1", port=0)
    coord_port = free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    try:
        procs = [
            subprocess.Popen(
                [sys.executable, str(script), str(r), str(kv.port), str(coord_port)],
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
                env=env,
                cwd=str(tmp_path),
            )
            for r in range(2)
        ]
        outs = []
        for p in procs:
            out, err = p.communicate(timeout=240)
            assert p.returncode == 0, f"child failed:\n{out}\n{err}"
            outs.append(out)
    finally:
        kv.close()
        for p in procs:
            if p.poll() is None:
                p.kill()

    results = {}
    for out in outs:
        line = [ln for ln in out.splitlines() if ln.startswith("RESULT ")][0]
        r = json.loads(line[len("RESULT "):])
        results[r["rank"]] = r

    for rank in (0, 1):
        r = results[rank]
        # Global visibility on every rank (the device pipeline always has the
        # global matrix): rank 1 scores clearly below rank 0 and is flagged.
        assert r["perf"]["1"] < 0.6 < r["perf"]["0"], r
        assert r["by_perf"] == [1], r
        assert r["perf2"]["1"] < r["perf2"]["0"], r
        # The globally-agreed column list drove the report.
        assert "sec/step" in r["sections"] and "sec/io" in r["sections"]
    # Both processes computed identical global scores from their own shards.
    assert results[0]["perf"] == pytest.approx(results[1]["perf"])


def test_mesh_telemetry_example_under_launcher(tmp_path):
    """The shipped product path: ``examples/mesh_telemetry_training.py`` under
    ``tpu-ft-launcher`` — the example itself asserts its report rounds made zero
    per-rank store gets and that the injected slow rank was flagged."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["TPU_RESILIENCY_LOG_LEVEL"] = "INFO"
    r = subprocess.run(
        [
            sys.executable, "-m", "tpu_resiliency.launcher.launch",
            "--nproc-per-node", "2",
            "--no-ft-monitors",
            "--rdzv-endpoint", f"127.0.0.1:{free_port()}",
            "--rdzv-last-call", "0.2",
            "--monitor-interval", "0.1",
            "--run-dir", str(tmp_path / "run"),
            os.path.join(REPO_ROOT, "examples", "mesh_telemetry_training.py"),
            "--coord-port", str(free_port()),
            "--steps", "150",
        ],
        capture_output=True,
        text=True,
        timeout=240,
        env=env,
        cwd=str(tmp_path),
    )
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    assert "ZERO-GATHER OK" in r.stdout
    assert "flagged ranks [1]" in r.stdout
