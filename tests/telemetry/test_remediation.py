"""RemediationEngine: the decision matrix, audit events, cooldown, and the
failure containment contract (an actuator bug never breaks detection)."""

import pytest

from tpu_resiliency.telemetry.policy import HealthDecision, HealthVectorPolicy
from tpu_resiliency.telemetry.remediation import (
    ACTION_CHECKPOINT,
    ACTION_EXCLUDE,
    ACTION_REINSTATE,
    ACTION_SPARE_SWAP,
    OUTCOME_FAILED,
    OUTCOME_OK,
    OUTCOME_SKIPPED,
    RemediationEngine,
)
from tpu_resiliency.telemetry.reporting import Report
from tpu_resiliency.utils import events


def decision(newly=(), degraded=None, recovered=()):
    newly = frozenset(newly)
    return HealthDecision(
        degraded=frozenset(degraded if degraded is not None else newly),
        newly_degraded=newly,
        recovered=frozenset(recovered),
        flagged=newly,
        scores={0: 1.0, 1: 0.4},
    )


@pytest.fixture
def seen():
    captured = []
    events.add_sink(captured.append)
    yield captured
    events.remove_sink(captured.append)


class TestDecisionMatrix:
    def test_checkpoint_always_first_when_wired(self, seen):
        order = []
        eng = RemediationEngine(
            checkpoint_fn=lambda: order.append("ckpt"),
            publish_degraded_fn=lambda d: order.append("publish"),
        )
        taken = eng.remediate(decision(newly={1}))
        assert [a for a, _ in taken] == [ACTION_CHECKPOINT, ACTION_EXCLUDE]
        assert order == ["ckpt", "publish"]
        assert all(o == OUTCOME_OK for _, o in taken)

    def test_spare_swap_when_capacity_available(self):
        restarts = []
        eng = RemediationEngine(
            spare_capacity_fn=lambda: 2,
            publish_degraded_fn=lambda d: None,
            request_restart_fn=restarts.append,
        )
        taken = eng.remediate(decision(newly={1}))
        assert (ACTION_SPARE_SWAP, OUTCOME_OK) in taken
        assert restarts and "swap degraded ranks [1]" in restarts[0]

    def test_exclude_when_no_spares(self):
        published = []
        eng = RemediationEngine(
            spare_capacity_fn=lambda: 0,
            publish_degraded_fn=published.append,
            request_restart_fn=lambda r: pytest.fail("no swap without spares"),
        )
        taken = eng.remediate(decision(newly={1}))
        assert taken == [(ACTION_EXCLUDE, OUTCOME_OK)]
        assert published == [frozenset({1})]

    def test_exclude_self_sends_control_request(self):
        class FakeClient:
            def __init__(self):
                self.sent = []

            def send_workload_control_request(self, action, reason=""):
                self.sent.append((action, reason))

        client = FakeClient()
        eng = RemediationEngine(monitor_client=client, self_rank=1,
                                publish_degraded_fn=lambda d: None)
        eng.remediate(decision(newly={1}))
        from tpu_resiliency.watchdog.data import WorkloadAction

        assert client.sent and client.sent[0][0] is WorkloadAction.ExcludeThisNode
        # Another rank degrading must NOT make this node exclude itself.
        client.sent.clear()
        eng.remediate(decision(newly={0}, degraded={0, 1}))
        assert client.sent == []

    def test_reinstate_on_pure_recovery(self, seen):
        published = []
        eng = RemediationEngine(publish_degraded_fn=published.append)
        taken = eng.remediate(decision(newly=(), degraded=(), recovered={1}))
        assert taken == [(ACTION_REINSTATE, OUTCOME_OK)]
        assert published == [frozenset()]
        acts = [e for e in seen if e.kind == "remediation_action"]
        assert acts[0].payload["action"] == ACTION_REINSTATE

    def test_no_change_no_action(self):
        eng = RemediationEngine(publish_degraded_fn=lambda d: None)
        assert eng.remediate(decision(newly=())) == []


class TestAuditTrail:
    def test_every_action_emits_event_and_spans(self, seen):
        eng = RemediationEngine(
            checkpoint_fn=lambda: None, publish_degraded_fn=lambda d: None
        )
        eng.remediate(decision(newly={1}))
        kinds = [e.kind for e in seen]
        assert "remediation_decision" in kinds
        actions = [e.payload["action"] for e in seen if e.kind == "remediation_action"]
        assert actions == [ACTION_CHECKPOINT, ACTION_EXCLUDE]
        # Each action ran inside its own remediation.<action> span.
        spans = [e.payload.get("span") for e in seen if e.kind == "span_begin"]
        assert "remediation.decide" in spans
        assert f"remediation.{ACTION_CHECKPOINT}" in spans
        assert f"remediation.{ACTION_EXCLUDE}" in spans

    def test_actuator_failure_is_audited_not_raised(self, seen):
        def boom():
            raise RuntimeError("ckpt disk full")

        eng = RemediationEngine(
            checkpoint_fn=boom, publish_degraded_fn=lambda d: None
        )
        taken = eng.remediate(decision(newly={1}))
        assert (ACTION_CHECKPOINT, OUTCOME_FAILED) in taken
        # The matrix keeps going: exclude still ran.
        assert (ACTION_EXCLUDE, OUTCOME_OK) in taken
        failed = next(
            e for e in seen
            if e.kind == "remediation_action" and e.payload["outcome"] == OUTCOME_FAILED
        )
        assert "ckpt disk full" in failed.payload["detail"]

    def test_sink_entry_swallows_everything(self):
        eng = RemediationEngine()
        # No actuators wired at all: exclude raises internally; the sink
        # entry point must still return (the detection loop survives).
        eng(decision(newly={1}))
        assert (ACTION_EXCLUDE, OUTCOME_FAILED) in eng.history


class TestCooldownAndDryRun:
    def test_cooldown_audits_skip(self, seen):
        eng = RemediationEngine(
            publish_degraded_fn=lambda d: None, cooldown=3600.0
        )
        first = eng.remediate(decision(newly={1}))
        assert first == [(ACTION_EXCLUDE, OUTCOME_OK)]
        second = eng.remediate(decision(newly={0}, degraded={0, 1}))
        assert second == [(ACTION_EXCLUDE, OUTCOME_SKIPPED)]
        skipped = [
            e for e in seen
            if e.kind == "remediation_action"
            and e.payload["outcome"] == OUTCOME_SKIPPED
        ]
        assert skipped and skipped[0].payload["detail"] == "cooldown"

    def test_cooldown_does_not_suppress_own_plan(self):
        # A multi-action plan is ONE remediation: with cooldown enabled, the
        # proactive checkpoint must not cool down the swap in the same plan.
        restarts = []
        eng = RemediationEngine(
            checkpoint_fn=lambda: None,
            spare_capacity_fn=lambda: 1,
            publish_degraded_fn=lambda d: None,
            request_restart_fn=restarts.append,
            cooldown=3600.0,
        )
        taken = eng.remediate(decision(newly={1}))
        assert taken == [
            (ACTION_CHECKPOINT, OUTCOME_OK),
            (ACTION_SPARE_SWAP, OUTCOME_OK),
        ]
        assert len(restarts) == 1
        # The next decision lands inside the window: the whole plan skips.
        second = eng.remediate(decision(newly={0}, degraded={0, 1}))
        assert all(o == OUTCOME_SKIPPED for _, o in second)
        assert len(restarts) == 1

    def test_dry_run_never_actuates(self):
        eng = RemediationEngine(
            checkpoint_fn=lambda: pytest.fail("dry run must not checkpoint"),
            publish_degraded_fn=lambda d: pytest.fail("dry run must not publish"),
            dry_run=True,
        )
        taken = eng.remediate(decision(newly={1}))
        assert all(o == OUTCOME_SKIPPED for _, o in taken)


class TestPolicyIntegration:
    def _report(self, perf):
        return Report(
            rank=0, world_size=len(perf), iteration=0, section_names=("step",),
            relative_section_scores={"step": 1.0},
            individual_section_scores={"step": 1.0},
            perf_scores=dict(perf), z_scores={r: 0.0 for r in perf},
            ewma_scores=dict(perf),
        )

    def test_policy_drives_engine_end_to_end(self, seen):
        history_at_demote = []
        eng = RemediationEngine(
            checkpoint_fn=lambda: None,
            publish_degraded_fn=lambda d: history_at_demote.append(set(d)),
        )
        pol = HealthVectorPolicy(patience=2, recovery=1, sinks=[eng])
        slow = {0: 1.0, 1: 0.3}
        pol.observe(self._report(slow))
        assert eng.history == []  # patience not yet met
        pol.observe(self._report(slow))
        assert (ACTION_CHECKPOINT, OUTCOME_OK) in eng.history
        assert history_at_demote[0] == {1}
        pol.observe(self._report({0: 1.0, 1: 0.99}))
        assert (ACTION_REINSTATE, OUTCOME_OK) in eng.history
        assert history_at_demote[-1] == set()


class TestExecuteAction:
    """The external-drive path (autoscale PR): the controller routes its
    swap/exclude/checkpoint decisions through the engine's actuators with the
    same cooldown/dry-run audit semantics as a policy-driven plan."""

    def test_swap_drives_the_actuators(self, seen):
        restarts, published = [], []
        eng = RemediationEngine(
            spare_capacity_fn=lambda: 1,
            publish_degraded_fn=published.append,
            request_restart_fn=restarts.append,
        )
        action, outcome = eng.execute_action(
            ACTION_SPARE_SWAP, [2], scores={2: 0.3}, reason="autoscale swap"
        )
        assert (action, outcome) == (ACTION_SPARE_SWAP, OUTCOME_OK)
        assert restarts and published == [frozenset({2})]
        assert eng.history[-1] == (ACTION_SPARE_SWAP, OUTCOME_OK)
        ev = [e for e in seen if e.kind == "remediation_action"][-1]
        assert ev.payload["reason"] == "autoscale swap"

    def test_cooldown_and_dry_run_audit_skip(self, seen):
        eng = RemediationEngine(
            checkpoint_fn=lambda: None, cooldown=60.0,
        )
        assert eng.execute_action(ACTION_CHECKPOINT, []) == (
            ACTION_CHECKPOINT, OUTCOME_OK,
        )
        # Second call lands inside the cooldown: audited as skipped.
        assert eng.execute_action(ACTION_CHECKPOINT, []) == (
            ACTION_CHECKPOINT, OUTCOME_SKIPPED,
        )
        dry = RemediationEngine(checkpoint_fn=lambda: None, dry_run=True)
        assert dry.execute_action(ACTION_CHECKPOINT, []) == (
            ACTION_CHECKPOINT, OUTCOME_SKIPPED,
        )

    def test_failure_contained(self):
        eng = RemediationEngine(
            checkpoint_fn=lambda: (_ for _ in ()).throw(RuntimeError("no")),
        )
        assert eng.execute_action(ACTION_CHECKPOINT, []) == (
            ACTION_CHECKPOINT, OUTCOME_FAILED,
        )

    def test_unknown_action_rejected(self):
        with pytest.raises(ValueError):
            RemediationEngine().execute_action("teleport", [1])
