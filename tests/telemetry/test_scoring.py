import numpy as np
import pytest

import jax.numpy as jnp

from tpu_resiliency.telemetry import scoring


def _mk_windows(rng, r, s, w, base=10.0):
    data = base + rng.standard_normal((r, s, w)).astype(np.float32) * 0.1
    counts = np.full((r, s), w, dtype=np.int32)
    return data, counts


def test_masked_median_matches_numpy():
    rng = np.random.default_rng(0)
    data = rng.uniform(1, 5, size=(4, 3, 9)).astype(np.float32)
    counts = np.array([[9, 5, 1], [2, 9, 4], [0, 3, 9], [9, 9, 9]], dtype=np.int32)
    med = np.asarray(scoring.masked_median(jnp.asarray(data), jnp.asarray(counts)))
    for i in range(4):
        for j in range(3):
            c = counts[i, j]
            if c == 0:
                assert np.isinf(med[i, j])
            else:
                np.testing.assert_allclose(med[i, j], np.median(data[i, j, :c]), rtol=1e-6)


def test_masked_total():
    data = jnp.asarray([[[1.0, 2.0, 100.0]]])
    counts = jnp.asarray([[2]], dtype=jnp.int32)
    assert float(scoring.masked_total(data, counts)[0, 0]) == 3.0


def test_relative_scores_flag_slow_rank():
    rng = np.random.default_rng(1)
    r, s, w = 8, 4, 16
    data, counts = _mk_windows(rng, r, s, w)
    data[3] *= 2.0  # rank 3 is 2x slower on every signal
    res = scoring.score_round(
        jnp.asarray(data),
        jnp.asarray(counts),
        prev_ewma=jnp.ones(r),
        historical_min=jnp.full((r, s), jnp.inf),
    )
    perf = np.asarray(res.perf)
    assert perf[3] == pytest.approx(0.5, abs=0.05)
    assert np.all(perf[np.arange(r) != 3] > 0.9)
    straggler = np.asarray(res.straggler)
    assert straggler[3]
    assert not straggler[np.arange(r) != 3].any()


def test_robust_z_detects_outlier_even_above_threshold():
    """A rank only mildly slow (score above 0.75) is still caught by robust-z."""
    rng = np.random.default_rng(2)
    r, s, w = 64, 4, 16
    data, counts = _mk_windows(rng, r, s, w)
    data[10] *= 1.15  # 15% slow: score ~0.87 > 0.75 threshold
    res = scoring.score_round(
        jnp.asarray(data),
        jnp.asarray(counts),
        prev_ewma=jnp.ones(r),
        historical_min=jnp.full((r, s), jnp.inf),
    )
    assert float(np.asarray(res.perf)[10]) > scoring.DEFAULT_THRESHOLD
    assert np.asarray(res.straggler)[10]  # caught by z
    assert np.asarray(res.straggler).sum() == 1


def test_individual_scores_track_historical_min():
    r, s, w = 2, 1, 4
    fast = np.full((r, s, w), 1.0, dtype=np.float32)
    counts = np.full((r, s), w, dtype=np.int32)
    res1 = scoring.score_round(
        jnp.asarray(fast),
        jnp.asarray(counts),
        prev_ewma=jnp.ones(r),
        historical_min=jnp.full((r, s), jnp.inf),
    )
    np.testing.assert_allclose(np.asarray(res1.individual_section_scores), 1.0)
    slow = fast * 4.0
    res2 = scoring.score_round(
        jnp.asarray(slow),
        jnp.asarray(counts),
        prev_ewma=res1.ewma,
        historical_min=res1.historical_min,
    )
    np.testing.assert_allclose(np.asarray(res2.individual_section_scores), 0.25)
    # relative scores see all ranks equally slow -> 1.0
    np.testing.assert_allclose(np.asarray(res2.section_scores), 1.0)


def test_empty_signals_score_neutral():
    r, s, w = 4, 3, 8
    rng = np.random.default_rng(3)
    data, counts = _mk_windows(rng, r, s, w)
    counts[:, 2] = 0  # nobody measured signal 2
    counts[1, 1] = 0  # rank 1 missed signal 1
    res = scoring.score_round(
        jnp.asarray(data),
        jnp.asarray(counts),
        prev_ewma=jnp.ones(r),
        historical_min=jnp.full((r, s), jnp.inf),
    )
    sec = np.asarray(res.section_scores)
    assert np.all(np.isfinite(np.asarray(res.perf)))
    np.testing.assert_allclose(sec[:, 2], 1.0)
    np.testing.assert_allclose(sec[1, 1], 1.0)
    assert not np.asarray(res.straggler).any()


def test_ewma_smoothing():
    r, s, w = 2, 1, 4
    data = np.ones((r, s, w), dtype=np.float32)
    counts = np.full((r, s), w, dtype=np.int32)
    res = scoring.score_round(
        jnp.asarray(data),
        jnp.asarray(counts),
        prev_ewma=jnp.zeros(r),
        historical_min=jnp.full((r, s), jnp.inf),
        alpha=0.5,
    )
    np.testing.assert_allclose(np.asarray(res.ewma), 0.5)


def test_pallas_kernel_matches_reference_pipeline():
    from tpu_resiliency.ops.scoring_pallas import fused_median_weights

    rng = np.random.default_rng(4)
    r, s, w = 16, 8, 16
    data, counts = _mk_windows(rng, r, s, w)
    counts[0, 0] = 5
    counts[2, 3] = 0
    counts[5, 1] = 1
    med_k, wt_k = fused_median_weights(
        jnp.asarray(data), jnp.asarray(counts), rank_tile=8, interpret=True
    )
    med_ref = scoring.masked_median(jnp.asarray(data), jnp.asarray(counts))
    wt_ref = scoring.masked_total(jnp.asarray(data), jnp.asarray(counts))
    np.testing.assert_allclose(np.asarray(med_k), np.asarray(med_ref), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(wt_k), np.asarray(wt_ref), rtol=1e-5)


def test_pallas_kernel_with_duplicates():
    from tpu_resiliency.ops.scoring_pallas import fused_median_weights

    data = np.full((4, 2, 8), 3.0, dtype=np.float32)
    counts = np.full((4, 2), 8, dtype=np.int32)
    med, wt = fused_median_weights(
        jnp.asarray(data), jnp.asarray(counts), rank_tile=4, interpret=True
    )
    np.testing.assert_allclose(np.asarray(med), 3.0)
    np.testing.assert_allclose(np.asarray(wt), 24.0)


def test_pallas_pairwise_mode_matches_loop_mode():
    """The all-pairs formulation is the same function as the rank-counting loop —
    including empty windows, single samples, and ties."""
    from tpu_resiliency.ops.scoring_pallas import fused_median_weights

    rng = np.random.default_rng(9)
    r, s, w = 16, 8, 16
    data, counts = _mk_windows(rng, r, s, w)
    counts[0, 0] = 5
    counts[2, 3] = 0
    counts[5, 1] = 1
    data[7, 2, :] = 1.5  # ties across the whole window

    loop = fused_median_weights(
        jnp.asarray(data), jnp.asarray(counts), interpret=True, mode="loop"
    )
    pair = fused_median_weights(
        jnp.asarray(data), jnp.asarray(counts), interpret=True, mode="pairwise"
    )
    np.testing.assert_allclose(np.asarray(loop[0]), np.asarray(pair[0]), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(loop[1]), np.asarray(pair[1]), rtol=1e-6)


def test_pallas_pairwise_large_s_fold_matches_numpy():
    """S>32 routes pairwise through the signal→rank fold (Mosaic rejects the 4-D
    all-pairs block past S=32); the double reshape must keep every (rank, signal)
    group's median in place — for the production S=64 and a non-divisible S=48
    (folded at the largest divisor ≤32, here 24)."""
    from tpu_resiliency.ops.scoring_pallas import fused_median_weights

    rng = np.random.default_rng(11)
    for s in (64, 48):
        r, w = 8, 16
        data, counts = _mk_windows(rng, r, s, w)
        counts[0, 0] = 3
        counts[1, s - 1] = 0
        med, wt = fused_median_weights(
            jnp.asarray(data), jnp.asarray(counts), interpret=True, mode="pairwise"
        )
        exp_med = np.full((r, s), np.inf, np.float32)
        exp_wt = np.zeros((r, s), np.float32)
        for i in range(r):
            for j in range(s):
                n = counts[i, j]
                exp_wt[i, j] = data[i, j, :n].sum()
                if n > 0:
                    exp_med[i, j] = np.median(data[i, j, :n])
        np.testing.assert_allclose(np.asarray(med), exp_med, rtol=1e-6)
        np.testing.assert_allclose(np.asarray(wt), exp_wt, rtol=1e-5)


def test_pallas_pairwise_prime_s_rejected():
    """A near-prime S>32 would fold to single-signal blocks — rejected loudly
    rather than silently running a pathological grid."""
    import pytest

    from tpu_resiliency.ops.scoring_pallas import fused_median_weights

    data = jnp.ones((8, 37, 8), jnp.float32)
    counts = jnp.full((8, 37), 8, jnp.int32)
    with pytest.raises(ValueError, match="divisor"):
        fused_median_weights(data, counts, interpret=True, mode="pairwise")


def test_pallas_radix_mode_matches_loop_mode():
    """The radix-select formulation is the same function as the rank-counting
    loop — including empty windows, single samples, and whole-window ties."""
    from tpu_resiliency.ops.scoring_pallas import fused_median_weights

    rng = np.random.default_rng(11)
    r, s, w = 16, 8, 16
    data, counts = _mk_windows(rng, r, s, w)
    counts[0, 0] = 5
    counts[2, 3] = 0
    counts[5, 1] = 1
    data[7, 2, :] = 1.5  # ties across the whole window
    data[3, 4, :] = np.float32(1e-30)  # subnormal-adjacent magnitudes

    loop = fused_median_weights(
        jnp.asarray(data), jnp.asarray(counts), interpret=True, mode="loop"
    )
    radix = fused_median_weights(
        jnp.asarray(data), jnp.asarray(counts), interpret=True, mode="radix"
    )
    np.testing.assert_array_equal(np.asarray(loop[0]), np.asarray(radix[0]))
    np.testing.assert_allclose(np.asarray(loop[1]), np.asarray(radix[1]), rtol=1e-6)


def test_pallas_radix_large_window_matches_numpy():
    """W=128/W=192 (beyond the quadratic cap, incl. non-power-of-two): the radix
    kernel must agree with numpy's median exactly on the valid prefix."""
    from tpu_resiliency.ops.scoring_pallas import fused_median_weights

    rng = np.random.default_rng(12)
    for w in (128, 192):
        r, s = 8, 4
        data = rng.uniform(0.5, 2.0, (r, s, w)).astype(np.float32)
        counts = rng.integers(0, w + 1, (r, s)).astype(np.int32)
        med, wt = fused_median_weights(
            jnp.asarray(data), jnp.asarray(counts), rank_tile=8,
            interpret=True, mode="radix",
        )
        med, wt = np.asarray(med), np.asarray(wt)
        for i in range(r):
            for j in range(s):
                n = counts[i, j]
                if n == 0:
                    assert med[i, j] == np.inf
                    assert wt[i, j] == 0.0
                else:
                    valid = np.sort(data[i, j, :n])
                    expect = 0.5 * (valid[(n - 1) // 2] + valid[n // 2])
                    assert med[i, j] == np.float32(expect), (i, j, n)
                    np.testing.assert_allclose(wt[i, j], data[i, j, :n].sum(), rtol=1e-5)


def test_radix_block_budget_shrinks_default_tile():
    """The radix default rank tile halves until the [RT, S, W] block fits the
    proven element budget (v5e compile fails at 32x64x256 blocks; 32x64x128 is
    proven), and the shrunk tile preserves the caller-checked divisibility.
    Explicit rank_tile is honored unchanged."""
    from tpu_resiliency.ops import scoring_pallas as sp
    from tpu_resiliency.ops.scoring_pallas import fused_median_weights

    assert sp.mode_rank_tile("radix", 64, 128) == 32  # largest proven block: no shrink
    assert sp.mode_rank_tile("radix", 64, 256) == 16  # one halving
    assert sp.mode_rank_tile("radix", 64, 512) == 8
    assert sp.mode_rank_tile("radix", 1, 32) == 32  # tiny shapes never shrink
    assert sp.mode_rank_tile("radix", 64, 2**20) == 1  # halving helper floors at 1...
    # ...but a single rank-row over budget is rejected outright: no tile fits.
    assert sp._snap_tile("radix", 32, 64, 8192) is None
    assert not sp.pallas_supported(32, mode="radix", window=8192, signals=64)
    with pytest.raises(ValueError, match="radix mode at window 8192"):
        fused_median_weights(
            jnp.zeros((2, 64, 8192), jnp.float32),
            jnp.zeros((2, 64), jnp.int32),
            interpret=True,
            mode="radix",
        )

    # A rank count the gate admits (R % min(32, R) == 0) but the shrunk tile
    # does not divide: the default path snaps to the largest dividing tile
    # instead of raising at score time (gate has no S to mirror the shrink
    # unless told the signal count).
    r, s, w = 24, 64, 256
    assert sp.pallas_supported(r, mode="radix", window=w)
    assert sp.pallas_supported(r, mode="radix", window=w, signals=s)
    rng24 = np.random.default_rng(5)
    d24 = rng24.uniform(0.5, 2.0, (r, s, w)).astype(np.float32)
    c24 = rng24.integers(0, w + 1, (r, s)).astype(np.int32)
    m24, _ = fused_median_weights(
        jnp.asarray(d24), jnp.asarray(c24), interpret=True, mode="radix",
    )
    n = c24[17, 33]
    v = np.sort(d24[17, 33, :n])
    assert np.asarray(m24)[17, 33] == np.float32(0.5 * (v[(n - 1) // 2] + v[n // 2]))

    # Near-prime R past the budget: the snap would shatter the grid into
    # [1, S, W] blocks — both gate (when it knows S) and kernel reject loudly
    # instead of silently running far slower than the XLA sort.
    assert sp._snap_tile("radix", 31, 64, 256) is None
    assert not sp.pallas_supported(31, mode="radix", window=256, signals=64)
    assert sp.pallas_supported(31, mode="radix", window=256)  # S unknown: permissive
    with pytest.raises(ValueError, match="radix mode at window 256"):
        fused_median_weights(
            jnp.zeros((31, 64, 256), jnp.float32),
            jnp.zeros((31, 64), jnp.int32),
            interpret=True,
            mode="radix",
        )
    # Small worlds are NOT degenerate: one whole-R block is a single grid step.
    assert sp._snap_tile("radix", 4, 64, 256) == 4
    assert sp.pallas_supported(4, mode="radix", window=256, signals=64)

    # Explicit rank_tile is honored unchanged through the budget path: the
    # caller asked for 32-rank blocks at W=256 and must get them.
    r32 = 32
    d32 = rng24.uniform(0.5, 2.0, (r32, s, w)).astype(np.float32)
    c32 = rng24.integers(0, w + 1, (r32, s)).astype(np.int32)
    m_def, _ = fused_median_weights(
        jnp.asarray(d32), jnp.asarray(c32), interpret=True, mode="radix",
    )
    m_exp, _ = fused_median_weights(
        jnp.asarray(d32), jnp.asarray(c32), interpret=True, mode="radix",
        rank_tile=32,
    )
    np.testing.assert_array_equal(np.asarray(m_def), np.asarray(m_exp))
    # ...and an explicit non-dividing tile hits the divisibility error — the
    # snap (which would have repaired 16 -> 12 at R=24) must not touch it.
    with pytest.raises(ValueError, match="not divisible"):
        fused_median_weights(
            jnp.asarray(d24), jnp.asarray(c24), interpret=True, mode="radix",
            rank_tile=16,
        )


def test_loop_block_budget_mirrors_radix_guard():
    """The loop kernel's proven block is 32x64x256; a many-signal config at the
    raised W=128 cap would exceed it at the default tile, so the same shrink /
    loud-reject machinery applies (the cap raise must not re-open an unproven
    VMEM regime)."""
    from tpu_resiliency.ops import scoring_pallas as sp
    from tpu_resiliency.ops.scoring_pallas import fused_median_weights

    # 32*256*128 = 2x the proven loop block: tile halves to 16.
    assert sp._snap_tile("loop", 32, 256, 128) == 16
    assert sp.pallas_supported(32, mode="loop", window=128, signals=256)
    rng = np.random.default_rng(21)
    r, s, w = 32, 256, 128
    data = rng.uniform(0.5, 2.0, (r, s, w)).astype(np.float32)
    counts = rng.integers(0, w + 1, (r, s)).astype(np.int32)
    med, _ = fused_median_weights(
        jnp.asarray(data), jnp.asarray(counts), interpret=True, mode="loop"
    )
    med = np.asarray(med)
    for i in range(0, r, 11):
        for j in range(0, s, 37):
            n = counts[i, j]
            if n:
                v = np.sort(data[i, j, :n])
                assert med[i, j] == np.float32(0.5 * (v[(n - 1) // 2] + v[n // 2]))
            else:
                assert med[i, j] == np.inf

    # A single rank-row past the loop budget (S*W > 32*64*256): gate rejects
    # (with signals), kernel raises loudly.
    assert sp._snap_tile("loop", 8, 4100, 128) is None
    assert not sp.pallas_supported(8, mode="loop", window=128, signals=4100)
    with pytest.raises(ValueError, match="loop mode at window"):
        fused_median_weights(
            jnp.zeros((2, 4100, 128), jnp.float32),
            jnp.zeros((2, 4100), jnp.int32),
            interpret=True,
            mode="loop",
        )


def test_radix_default_tile_end_to_end_at_failing_shape():
    """End-to-end at the shape whose compile failed on-device (32x64x256):
    the default radix tile must shrink to 16 and the kernel must still match
    numpy (interpret mode)."""
    from tpu_resiliency.ops.scoring_pallas import fused_median_weights

    rng = np.random.default_rng(3)
    r, s, w = 32, 64, 256
    data = rng.uniform(0.5, 2.0, (r, s, w)).astype(np.float32)
    counts = rng.integers(0, w + 1, (r, s)).astype(np.int32)
    med, wt = fused_median_weights(
        jnp.asarray(data), jnp.asarray(counts), interpret=True, mode="radix",
    )
    med = np.asarray(med)
    for i in range(0, r, 7):
        for j in range(0, s, 13):
            n = counts[i, j]
            if n == 0:
                assert med[i, j] == np.inf
            else:
                valid = np.sort(data[i, j, :n])
                expect = 0.5 * (valid[(n - 1) // 2] + valid[n // 2])
                assert med[i, j] == np.float32(expect), (i, j, n)


def test_pallas_window_gate(monkeypatch):
    """Auto-selection must not hand a large-window user an O(W^2) kernel: the
    quadratic modes cap at the measured crossover (env-overridable once the
    per-device sweep has run); mode-auto switches to radix instead of
    falling back to the XLA sort."""
    from tpu_resiliency.ops import scoring_pallas as sp

    # Shape gating alone (no window): unchanged behavior.
    assert sp.pallas_supported(32)
    assert not sp.pallas_supported(33)
    # Mode-auto: past the cap (measured at 128 on v5e) the mode would be
    # radix, but auto-selection requires the device-measured opt-in;
    # explicit radix always works.
    assert sp.pallas_supported(32, window=32)
    assert not sp.pallas_supported(32, window=256)
    assert sp.auto_mode(128) == "loop"
    assert sp.auto_mode(256) == "radix"
    monkeypatch.setenv(sp.RADIX_ENV, "on")
    assert sp.pallas_supported(32, window=256)
    assert sp.pallas_supported(32, window=512)
    monkeypatch.delenv(sp.RADIX_ENV)
    # Explicit quadratic modes stay capped.
    assert sp.pallas_supported(32, mode="loop", window=128)
    assert not sp.pallas_supported(32, mode="loop", window=256)
    # Pairwise carries its own measured bound (compiles only at W=32 on v5e),
    # independent of the loop cap.
    assert sp.pallas_supported(32, mode="pairwise", window=32)
    assert not sp.pallas_supported(32, mode="pairwise", window=64)
    assert not sp.pallas_supported(32, mode="pairwise", window=256)
    # ...and, when the gate knows S, the kernel's near-prime S-fold rejection
    # too (S=37 has no fold divisor in [8, 32]; S=48 folds at 24).
    assert not sp.pallas_supported(32, mode="pairwise", window=32, signals=37)
    assert sp.pallas_supported(32, mode="pairwise", window=32, signals=48)
    assert sp.pallas_supported(32, mode="radix", window=256)
    # Operator encoded a smaller measured crossover for their device: the
    # loop kernel's reach shrinks and auto-select hands W=64 to radix.
    monkeypatch.setenv(sp.MAX_WINDOW_ENV, "32")
    assert sp.auto_mode(64) == "radix"
    assert sp.pallas_supported(32, mode="loop", window=32)
    assert not sp.pallas_supported(32, mode="loop", window=64)
    monkeypatch.setenv(sp.MAX_WINDOW_ENV, "junk")
    assert sp.max_auto_window() == sp.DEFAULT_MAX_WINDOW


def test_mesh_telemetry_autoselect_large_window(monkeypatch):
    """MeshTelemetry(use_pallas=None) at large windows: XLA until the radix
    kernel's device measurement is opted in, then the Pallas radix path."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from tpu_resiliency.ops import scoring_pallas as sp
    from tpu_resiliency.telemetry.sharded import MeshTelemetry

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    mesh = Mesh(np.asarray(jax.devices()[:1]), ("rank",))
    try:
        mt_small = MeshTelemetry(mesh, "rank", n_ranks=32, window=32)
        mt_large = MeshTelemetry(mesh, "rank", n_ranks=32, window=256)
        monkeypatch.setenv(sp.RADIX_ENV, "on")
        mt_large_opted = MeshTelemetry(mesh, "rank", n_ranks=32, window=256)
    finally:
        monkeypatch.undo()
    assert mt_small.use_pallas is True
    assert mt_large.use_pallas is False
    assert mt_large_opted.use_pallas is True
