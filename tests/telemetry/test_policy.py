"""Health-vector policy: streak promotion, hysteresis recovery, sinks, and the
decision couplings (replication avoidance; DemoteDegraded is covered in
tests/inprocess)."""

import numpy as np
import pytest

from tpu_resiliency.checkpoint.replication import ExchangePlan
from tpu_resiliency.telemetry.policy import (
    HealthVectorPolicy,
    coordinator_sink,
)
from tpu_resiliency.telemetry.reporting import Report


def make_report(perf: dict[int, float], iteration=0) -> Report:
    world = len(perf)
    return Report(
        rank=0,
        world_size=world,
        iteration=iteration,
        section_names=("step",),
        relative_section_scores={"step": perf[0]},
        individual_section_scores={"step": 1.0},
        perf_scores=dict(perf),
        z_scores={r: 0.0 for r in perf},
        ewma_scores=dict(perf),
    )


HEALTHY = {0: 1.0, 1: 0.98, 2: 0.99, 3: 1.0}
SLOW2 = {0: 1.0, 1: 0.98, 2: 0.4, 3: 1.0}


class TestHealthVectorPolicy:
    def test_patience_before_degraded(self):
        p = HealthVectorPolicy(patience=2, recovery=2)
        d1 = p.observe(make_report(SLOW2))
        assert d1.flagged == {2} and d1.degraded == frozenset()
        d2 = p.observe(make_report(SLOW2))
        assert d2.newly_degraded == {2} and p.degraded == {2}

    def test_single_noisy_round_does_not_degrade(self):
        p = HealthVectorPolicy(patience=2, recovery=2)
        p.observe(make_report(SLOW2))
        d = p.observe(make_report(HEALTHY))  # clean round resets the streak
        assert d.degraded == frozenset()
        p.observe(make_report(SLOW2))
        assert p.degraded == frozenset()  # streak restarted at 1

    def test_recovery_hysteresis(self):
        p = HealthVectorPolicy(patience=1, recovery=3)
        p.observe(make_report(SLOW2))
        assert p.degraded == {2}
        p.observe(make_report(HEALTHY))
        p.observe(make_report(HEALTHY))
        assert p.degraded == {2}  # still held: recovery needs 3 clean rounds
        d = p.observe(make_report(HEALTHY))
        assert d.recovered == {2} and p.degraded == frozenset()

    def test_sink_called_on_change_only(self):
        seen = []
        p = HealthVectorPolicy(patience=1, recovery=1, sinks=[seen.append])
        p.observe(make_report(SLOW2))
        p.observe(make_report(SLOW2))  # no change: still degraded
        p.observe(make_report(HEALTHY))
        assert len(seen) == 2
        assert seen[0].newly_degraded == {2}
        assert seen[1].recovered == {2}

    def test_coordinator_sink_publishes(self, coord_store):
        from tpu_resiliency.inprocess.coordination import RestartCoordinator

        coord = RestartCoordinator(coord_store, world_size=4)
        p = HealthVectorPolicy(patience=1, recovery=1, sinks=[coordinator_sink(coord)])
        p.observe(make_report(SLOW2))
        assert coord.degraded_ranks() == {2}
        p.observe(make_report(HEALTHY))
        assert coord.degraded_ranks() == frozenset()

    def test_validation(self):
        with pytest.raises(ValueError):
            HealthVectorPolicy(patience=0)


class TestHysteresisEdges:
    """The edges remediation depends on: streaks across restart rounds,
    simultaneous transitions, and the one-event-per-transition contract."""

    def test_recovery_streak_does_not_survive_a_restart_round(self):
        p = HealthVectorPolicy(patience=1, recovery=3)
        p.observe(make_report(SLOW2))
        assert p.degraded == {2}
        p.observe(make_report(HEALTHY))
        p.observe(make_report(HEALTHY))  # 2 of 3 clean rounds banked
        p.note_restart()                 # respawned rank has proven nothing
        p.observe(make_report(HEALTHY))
        assert p.degraded == {2}, "pre-restart clean streak wrongly carried"
        p.observe(make_report(HEALTHY))
        p.observe(make_report(HEALTHY))
        assert p.degraded == frozenset()  # 3 fresh post-restart rounds clear it

    def test_flag_streak_does_not_survive_a_restart_round(self):
        p = HealthVectorPolicy(patience=2, recovery=1)
        p.observe(make_report(SLOW2))    # streak 1 of 2
        p.note_restart()
        d = p.observe(make_report(SLOW2))  # fresh streak 1, NOT promotion
        assert d.degraded == frozenset()
        d = p.observe(make_report(SLOW2))
        assert d.newly_degraded == {2}

    def test_degraded_status_persists_across_restart(self):
        p = HealthVectorPolicy(patience=1, recovery=2)
        p.observe(make_report(SLOW2))
        p.note_restart()
        assert p.degraded == {2}  # hysteresis resets, the verdict does not

    def test_simultaneous_degrade_and_recover_in_one_observation(self):
        p = HealthVectorPolicy(patience=1, recovery=1)
        p.observe(make_report(SLOW2))            # rank 2 degraded
        both = {0: 1.0, 1: 0.4, 2: 1.0, 3: 1.0}  # 1 degrades AS 2 recovers
        d = p.observe(make_report(both))
        assert d.newly_degraded == {1}
        assert d.recovered == {2}
        assert d.degraded == {1}
        assert d.changed

    def test_every_transition_emits_its_event(self):
        from tpu_resiliency.utils import events

        seen = []
        events.add_sink(seen.append)
        try:
            p = HealthVectorPolicy(patience=1, recovery=1)
            p.observe(make_report(SLOW2))    # transition: degrade
            p.observe(make_report(SLOW2))    # steady state: no event
            p.observe(make_report(HEALTHY))  # transition: recover
            p.observe(make_report(HEALTHY))  # steady state: no event
        finally:
            events.remove_sink(seen.append)
        transitions = [e for e in seen if e.kind == "degraded_set"]
        assert len(transitions) == 2
        assert transitions[0].payload["newly"] == [2]
        assert transitions[0].payload["recovered"] == []
        assert transitions[1].payload["recovered"] == [2]
        # The event carries the scores that justified the transition.
        assert transitions[0].payload["scores"]["2"] == pytest.approx(0.4)

    def test_decision_carries_scores_for_downstream_audit(self):
        p = HealthVectorPolicy(patience=1, recovery=1)
        d = p.observe(make_report(SLOW2))
        assert d.scores[2] == pytest.approx(0.4)


class TestDemoteDegraded:
    def _ctx(self, world, terminated=(), degraded=(), rank=0):
        from tpu_resiliency.inprocess.rank_assignment import RankAssignmentCtx
        from tpu_resiliency.inprocess.state import State

        st = State(rank=rank, world_size=world)
        return RankAssignmentCtx(st, frozenset(terminated), frozenset(degraded))

    def test_degraded_yields_to_healthy(self):
        from tpu_resiliency.inprocess.rank_assignment import DemoteDegraded
        from tpu_resiliency.inprocess.state import Mode

        # world 4, cap 3, rank 1 degraded: actives are 0,2,3; rank 1 reserves.
        ctx = DemoteDegraded(3)(self._ctx(4, degraded={1}, rank=1))
        assert ctx.state.mode is Mode.INACTIVE and ctx.state.active_rank is None
        ctx = DemoteDegraded(3)(self._ctx(4, degraded={1}, rank=3))
        assert ctx.state.mode is Mode.ACTIVE and ctx.state.active_rank == 2

    def test_degraded_fills_in_when_no_healthy_spare(self):
        from tpu_resiliency.inprocess.rank_assignment import DemoteDegraded
        from tpu_resiliency.inprocess.state import Mode

        # world 3, cap 3: the degraded rank must stay active (slow beats absent),
        # but is renumbered last.
        ctx = DemoteDegraded(3)(self._ctx(3, degraded={0}, rank=0))
        assert ctx.state.mode is Mode.ACTIVE and ctx.state.active_rank == 2


class TestExcludeSelfSink:
    def test_fires_only_on_own_demotion(self):
        from tpu_resiliency.telemetry.policy import exclude_self_sink

        class FakeClient:
            def __init__(self):
                self.sent = []
                self.rank_info = None

            def send_workload_control_request(self, action, reason=""):
                self.sent.append((action, reason))

        client = FakeClient()
        p = HealthVectorPolicy(
            patience=1, recovery=1, sinks=[exclude_self_sink(client, rank=2)]
        )
        p.observe(make_report(SLOW2))
        assert len(client.sent) == 1
        from tpu_resiliency.watchdog.data import WorkloadAction

        assert client.sent[0][0] is WorkloadAction.ExcludeThisNode
        # Recovery does not re-fire the exclusion.
        p.observe(make_report(HEALTHY))
        assert len(client.sent) == 1


class TestReplicationAvoidsDegraded:
    def test_healthy_holder_preferred(self):
        # Rank 0 lost its shard; ranks 1 (degraded) and 2 (healthy) both hold it.
        plan = ExchangePlan.build(
            wanted={0: 0}, holders={1: {0}, 2: {0}}, avoid={1}
        )
        assert plan.recvs[0] == [(2, 0)]

    def test_degraded_holder_used_as_last_resort(self):
        plan = ExchangePlan.build(wanted={0: 0}, holders={1: {0}}, avoid={1})
        assert plan.recvs[0] == [(1, 0)]

    def test_load_balance_within_health_class(self):
        # Two healthy holders: load spreads between them even with a degraded third.
        plan = ExchangePlan.build(
            wanted={0: 0, 3: 0},
            holders={1: {0}, 2: {0}, 4: {0}},
            avoid={4},
        )
        srcs = sorted(src for (src, _) in [plan.recvs[0][0], plan.recvs[3][0]])
        assert srcs == [1, 2]
