"""Fleet aggregation over real (loopback) job telemetry endpoints: merged
metrics with job labels + fleet totals, scoreboard/SLO/incident/hangz folds,
per-job failure containment, churn semantics, bucket quantiles."""

import json
import os
import time

import pytest

from tpu_resiliency.fleet.aggregator import (
    FLEET_TOTAL_PREFIX,
    FleetAggregator,
    bucket_quantile,
)
from tpu_resiliency.fleet.registry import live_leases, read_leases
from tpu_resiliency.launcher.telemetry import TelemetryServer
from tpu_resiliency.utils import events


@pytest.fixture(autouse=True)
def clean_sinks():
    events.clear_sinks()
    old = os.environ.pop(events.EVENTS_FILE_ENV, None)
    yield
    events.clear_sinks()
    if old is not None:
        os.environ[events.EVENTS_FILE_ENV] = old


def start_job(tmp_path, job, *, restarts=0, steps=0):
    """One registered job: a real TelemetryServer with a fleet lease and some
    registry state to federate."""
    srv = TelemetryServer(
        port=0,
        fleet_dir=str(tmp_path / "fleet"),
        job=job,
        node_id=f"node-{job}",
        events_file=str(tmp_path / f"{job}.jsonl"),
        lease_interval=0.2,
    )
    srv.start()
    if restarts:
        srv.registry.counter(
            "tpu_restarts_total", "restarts", layer="injob"
        ).inc(restarts)
    if steps:
        t0 = time.time() - steps
        with open(tmp_path / f"{job}.jsonl", "w") as f:
            for i in range(steps + 1):
                f.write(json.dumps({
                    "kind": "iteration_start", "iteration": i, "ts": t0 + i,
                    "pid": 1, "rank": 0,
                }) + "\n")
    return srv


def test_scrape_folds_jobs_with_labels_and_totals(tmp_path):
    a = start_job(tmp_path, "job-a", restarts=2, steps=4)
    b = start_job(tmp_path, "job-b", restarts=3)
    agg = FleetAggregator(str(tmp_path / "fleet"))
    try:
        view = agg.scrape()
        prom = view.to_prometheus()
        # The regression the satellite names: same-named series stay separate
        # per job AND sum in the explicit fleet-total family.
        assert 'tpu_restarts_total{job="job-a",layer="injob"} 2' in prom
        assert 'tpu_restarts_total{job="job-b",layer="injob"} 3' in prom
        assert f'{FLEET_TOTAL_PREFIX}tpu_restarts_total{{layer="injob"}} 5' in prom
        # fleetd's own operational metrics ride the same registry.
        assert "tpu_fleet_jobs 2" in prom
        assert "tpu_fleet_scrape_seconds_count 1" in prom
    finally:
        a.stop()
        b.stop()


def test_goodput_scoreboard_ranks_by_ratio(tmp_path):
    a = start_job(tmp_path, "job-a", steps=5)  # trained: ratio 1.0
    b = start_job(tmp_path, "job-b")           # idle: ratio 0.0
    agg = FleetAggregator(str(tmp_path / "fleet"))
    try:
        doc = agg.scrape().goodput_doc()
        assert doc["schema"] == "tpu-fleet-goodput-1"
        assert [r["job"] for r in doc["jobs"]] == ["job-a", "job-b"]
        assert doc["jobs"][0]["goodput_ratio"] == pytest.approx(1.0)
        assert all(r["status"] == "ok" for r in doc["jobs"])
        assert doc["fleet"]["jobs"] == 2 and doc["fleet"]["reachable"] == 2
        assert doc["fleet"]["goodput_ratio"] > 0
    finally:
        a.stop()
        b.stop()


def test_dead_job_is_unreachable_never_fatal(tmp_path):
    """One crashed job (lease present, endpoint gone) = one unreachable row
    + a fleet_job_unreachable audit; the fold itself never fails."""
    a = start_job(tmp_path, "job-a", steps=3)
    dead = start_job(tmp_path, "job-dead")
    # Simulate SIGKILL: the HTTP endpoint dies, the lease file stays behind
    # (a killed process removes nothing).
    dead._lease_stop.set()
    dead._lease_thread.join(timeout=5)
    dead._httpd.shutdown()
    dead._httpd.server_close()
    agg = FleetAggregator(str(tmp_path / "fleet"), timeout=1.0)
    try:
        view = agg.scrape()
        by_job = {s["job"]: s for s in view.states}
        assert by_job["job-a"]["reachable"] is True
        assert by_job["job-dead"]["reachable"] is False
        assert by_job["job-dead"]["error"]
        gp = view.goodput_doc()
        # Unreachable rows sort last and say why.
        assert gp["jobs"][-1]["job"] == "job-dead"
        assert gp["jobs"][-1]["status"] == "unreachable"
        # The SLO page leads with the unreachable job (it IS the incident).
        assert view.slo_doc()["jobs"][0]["job"] == "job-dead"
        assert "tpu_fleet_scrape_errors_total" in view.to_prometheus()
        assert agg.registry.counter(
            "tpu_fleet_scrape_errors_total", "", job="job-dead"
        ).value == 1
    finally:
        a.stop()


def test_churn_no_duplicate_rows_and_no_double_count(tmp_path):
    """The churn satellite: a job that dies, expires, and re-registers under
    the same rdzv id mid-scrape-loop yields exactly one scoreboard row per
    scrape and never double-counts its counters."""
    fleet = str(tmp_path / "fleet")
    agg = FleetAggregator(fleet, lease_ttl=60.0, timeout=1.0)
    first = start_job(tmp_path, "job-x", restarts=1)
    assert len(agg.scrape().goodput_doc()["jobs"]) == 1
    # Crash (no lease removal), then a new incarnation re-registers the SAME
    # job id from a new pid/port before the old lease expired.
    first._lease_stop.set()
    first._lease_thread.join(timeout=5)
    first._httpd.shutdown()
    first._httpd.server_close()
    # The dead incarnation's leftover lease, under its own (pid-distinct in
    # production; both incarnations share this test process's pid) filename,
    # heartbeat slightly behind the replacement's.
    doc = json.loads(open(first._lease.path).read())
    doc["pid"], doc["heartbeat_ts"] = 99999, time.time() - 1.0
    old_lease_path = os.path.join(fleet, "job-job-x-99999.json")
    with open(old_lease_path, "w") as f:
        json.dump(doc, f)
    second = start_job(tmp_path, "job-x", restarts=4)
    try:
        assert len(read_leases(fleet)) == 2  # two files on disk...
        assert len(live_leases(fleet, ttl=60.0)) == 1  # ...one live identity
        view = agg.scrape()
        rows = view.goodput_doc()["jobs"]
        assert [r["job"] for r in rows] == ["job-x"]  # no duplicate row
        assert rows[0]["status"] == "ok"
        # Only the live incarnation's counters are in the fold — the dead
        # lease is not scraped, so nothing double-counts.
        assert view.registry.counter(
            "tpu_restarts_total", "", layer="injob", job="job-x"
        ).value == 4
        # Expiry: once the dead lease goes stale, the scrape loop unlinks it.
        doc["heartbeat_ts"] = time.time() - 100.0
        with open(old_lease_path, "w") as f:
            json.dump(doc, f)
        agg.lease_ttl = 15.0
        agg.scrape()
        assert not os.path.exists(old_lease_path)
        assert len(read_leases(fleet)) == 1
    finally:
        second.stop()


def test_incidents_and_hangz_fold(tmp_path):
    inc_dir = tmp_path / "incidents"
    inc_dir.mkdir()
    art = {
        "schema": "tpu-incident-1", "id": "incident-1-1", "trigger": "hang",
        "detail": "", "outcome": "recovered", "ranks": [1],
        "opened_ts": 100.0, "closed_ts": 101.0, "fault_ts": 99.5,
        "slo": {"time_to_detect_s": 0.5, "time_to_recover_s": 1.5},
        "chain": [{}], "events": [{}, {}], "flight": {},
    }
    (inc_dir / "incident-1-1.json").write_text(json.dumps(art))
    srv = TelemetryServer(
        port=0, fleet_dir=str(tmp_path / "fleet"), job="job-a",
        incidents_dir=str(inc_dir),
    )
    srv.census_fn = lambda: {
        "schema": "tpu-hangz-1",
        "suspects": [{"rank": 1, "score": 2.0, "reasons": ["missing"]}],
        "ranks": [], "barriers": [],
    }
    srv.start()
    agg = FleetAggregator(str(tmp_path / "fleet"))
    try:
        view = agg.scrape()
        inc = view.incidents_doc()
        assert inc["schema"] == "tpu-fleet-incidents-1"
        assert len(inc["incidents"]) == 1
        row = inc["incidents"][0]
        assert row["job"] == "job-a" and row["trigger"] == "hang"
        assert row["events"] == 2  # heavy fields trimmed to counts
        assert inc["jobs"] == {"job-a": 1}
        hz = view.hangz_doc()
        assert hz["schema"] == "tpu-fleet-hangz-1"
        assert hz["suspects"] == [
            {"job": "job-a", "rank": 1, "score": 2.0, "reasons": ["missing"]}
        ]
    finally:
        srv.stop()


def test_slo_percentiles_from_merged_buckets(tmp_path):
    srv = start_job(tmp_path, "job-a", steps=3)
    for v in (0.2, 0.4, 8.0):
        srv.registry.histogram(
            "tpu_incident_time_to_detect_seconds", "ttd"
        ).observe(v)
    agg = FleetAggregator(str(tmp_path / "fleet"))
    try:
        row = agg.scrape().slo_doc()["jobs"][0]
        ttd = row["time_to_detect_s"]
        assert ttd["count"] == 3
        assert 0.1 <= ttd["p50"] <= 0.5
        assert 5.0 <= ttd["p95"] <= 10.0
        assert row["restart_share"] is not None
    finally:
        srv.stop()


def test_empty_fleet_is_a_valid_answer(tmp_path):
    agg = FleetAggregator(str(tmp_path / "fleet"))
    view = agg.scrape()
    assert view.states == []
    assert view.goodput_doc()["fleet"]["jobs"] == 0
    assert view.slo_doc()["jobs"] == []
    assert "tpu_fleet_jobs 0" in view.to_prometheus()


# -- bucket_quantile ---------------------------------------------------------


def test_bucket_quantile_interpolates():
    bounds = (1.0, 2.0, 4.0)
    counts = [0, 4, 0, 0]  # all four samples in (1, 2]
    assert bucket_quantile(bounds, counts, 0.5) == pytest.approx(1.5)
    assert bucket_quantile(bounds, counts, 1.0) == pytest.approx(2.0)


def test_bucket_quantile_edges():
    assert bucket_quantile((), [], 0.5) is None
    assert bucket_quantile((1.0,), [0, 0], 0.5) is None  # empty histogram
    # +Inf tail clamps to the highest finite bound.
    assert bucket_quantile((1.0, 2.0), [0, 0, 3], 0.5) == 2.0
    # first bucket interpolates from 0 (or the bound itself when negative)
    assert 0.0 < bucket_quantile((1.0, 2.0), [2, 0, 0], 0.5) <= 1.0
    assert bucket_quantile((-1.0, 1.0), [2, 0, 0], 0.99) <= -0.0


# -- /fleet/alerts fold -------------------------------------------------------


def _low_ratio_rule(name, severity):
    from tpu_resiliency.telemetry.watchtower import AlertRule

    return AlertRule(
        name=name,
        check=lambda store, now, p: (
            "ratio low"
            if any(v < 0.5 for _, v in store.query("tpu_goodput_ratio"))
            else None
        ),
        severity=severity,
    )


def test_fleet_alerts_feed_ranks_and_degrades(tmp_path):
    """The cross-job alert feed: pages lead, firing jobs are counted, and an
    unreachable job degrades to its row instead of vanishing."""
    from tpu_resiliency.telemetry.watchtower import Watchtower

    a = start_job(tmp_path, "job-a")
    b = start_job(tmp_path, "job-b")
    a.watchtower = Watchtower(
        [_low_ratio_rule("hot", "page"), _low_ratio_rule("warm", "warn")],
        job="job-a",
    )
    b.watchtower = Watchtower([_low_ratio_rule("hot", "page")], job="job-b")
    for job, ratio in (("job-a", 0.2), ("job-b", 1.0)):
        with open(tmp_path / f"{job}.jsonl", "w") as f:
            for ts in (100.0, 120.0):
                f.write(json.dumps({
                    "kind": "goodput_update", "ts": ts, "ratio": ratio,
                    "pid": 1,
                }) + "\n")
    agg = FleetAggregator(str(tmp_path / "fleet"), timeout=1.0)
    try:
        doc = agg.scrape().alerts_doc()
        assert doc["schema"] == "tpu-fleet-alerts-1"
        # Severity-ranked: the page leads the warn even within one job.
        assert [(r["job"], r["rule"], r["severity"]) for r in doc["active"]] \
            == [("job-a", "hot", "page"), ("job-a", "warm", "warn")]
        assert doc["firing_jobs"] == {"job-a": 2}
        rows = {r["job"]: r for r in doc["jobs"]}
        assert rows["job-a"]["active"] == 2 and rows["job-a"]["rules"] == 2
        assert rows["job-b"]["active"] == 0 and rows["job-b"]["rules"] == 1
        assert doc["unreachable"] == []
        # SIGKILL semantics: endpoint gone, lease behind — the job keeps its
        # row (status unreachable) and lands in the unreachable census.
        a._lease_stop.set()
        a._lease_thread.join(timeout=5)
        a._httpd.shutdown()
        a._httpd.server_close()
        agg.close()
        doc = agg.scrape().alerts_doc()
        rows = {r["job"]: r for r in doc["jobs"]}
        assert rows["job-a"]["status"] == "unreachable"
        assert rows["job-a"]["error"]
        assert "active" not in rows["job-a"]  # no doc, no counts to fake
        assert doc["unreachable"] == ["job-a"]
        assert [(r["job"], r["rule"]) for r in doc["active"]] == []
        assert doc["firing_jobs"] == {}
    finally:
        a.stop()
        b.stop()


def test_fleet_alerts_feed_empty_fleet(tmp_path):
    doc = FleetAggregator(str(tmp_path / "fleet")).scrape().alerts_doc()
    assert doc["schema"] == "tpu-fleet-alerts-1"
    assert doc["active"] == [] and doc["jobs"] == []
    assert doc["firing_jobs"] == {} and doc["unreachable"] == []
