"""Slow-marked fleet acceptance gate: drives scripts/bench_fleet.py --smoke —
N real concurrent 2-rank chaos jobs on loopback, scrape cost sub-linear in
job count, SIGKILLed job contained as `unreachable` with every /fleet/*
endpoint still 200. A regression fails CI here, not in a JSON diff."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@pytest.mark.slow
def test_fleet_bench_smoke(tmp_path):
    out = tmp_path / "BENCH_fleet.json"
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "scripts", "bench_fleet.py"),
            "--smoke", "--out", str(out),
        ],
        capture_output=True,
        text=True,
        timeout=580,
    )
    assert proc.returncode == 0, (proc.stdout[-2000:], proc.stderr[-2000:])
    res = json.loads(out.read_text())
    # Sub-linear scrape cost: parallel fan-out + keep-alive + job-side
    # snapshot cache must beat the linear extrapolation by the bar.
    assert res["sublinear"]["ok"], res["sublinear"]
    # Crash containment: the SIGKILLed job never degraded a fleet endpoint.
    assert res["kill"]["all_200"], res["kill"]
    assert res["kill"]["victim_status"] == "unreachable", res["kill"]
    assert res["kill"]["survivors_ok"], res["kill"]
    # Every measured size actually saw its full fleet.
    sizes = res["config"]["sizes"]
    assert [r["jobs"] for r in res["scrape_cost"]] == sizes
