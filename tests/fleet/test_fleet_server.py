"""FleetServer endpoint contract + the acceptance story: every /fleet/*
endpoint keeps answering 200 while a job dies mid-scrape-loop, the dead job
reported `unreachable` instead of degrading the fleet view."""

import json
import os
import time
import urllib.error
import urllib.request

import pytest

from tpu_resiliency.fleet.aggregator import FleetAggregator
from tpu_resiliency.fleet.registry import JobLease, write_lease
from tpu_resiliency.fleet.server import PORT_FILE_NAME, FleetServer
from tpu_resiliency.launcher.telemetry import TelemetryServer
from tpu_resiliency.tools import fleet_cli
from tpu_resiliency.utils import events

FLEET_ENDPOINTS = (
    "/fleet/metrics", "/fleet/goodput", "/fleet/slo", "/fleet/incidents",
    "/fleet/hangz", "/fleet/alerts", "/fleet/snapshot",
)


@pytest.fixture(autouse=True)
def clean_sinks():
    events.clear_sinks()
    old = os.environ.pop(events.EVENTS_FILE_ENV, None)
    yield
    events.clear_sinks()
    if old is not None:
        os.environ[events.EVENTS_FILE_ENV] = old


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}", timeout=10) as r:
        return r.status, r.read().decode(), r.headers.get("Content-Type", "")


def _start_job(tmp_path, job):
    srv = TelemetryServer(
        port=0, fleet_dir=str(tmp_path / "fleet"), job=job,
        node_id=f"node-{job}", lease_interval=0.2,
    )
    srv.start()
    srv.registry.counter("tpu_ckpt_saves_total", "saves").inc(1)
    return srv


def _kill_job(srv, agg=None):
    """Simulate SIGKILL: endpoint vanishes, heartbeat stops, lease remains.
    An in-process shutdown leaves keep-alive handler threads running (a real
    process death would not — the kernel resets its sockets; the bench's
    real-SIGKILL leg covers that), so the scraper's kept-alive connections
    are dropped here the way the kernel would drop them."""
    srv._lease_stop.set()
    srv._lease_thread.join(timeout=5)
    srv._httpd.shutdown()
    srv._httpd.server_close()
    if agg is not None:
        agg.close()


@pytest.fixture
def fleet(tmp_path):
    jobs = [_start_job(tmp_path, j) for j in ("job-a", "job-b")]
    agg = FleetAggregator(str(tmp_path / "fleet"), timeout=1.0)
    srv = FleetServer(
        agg, port=0, scrape_ttl=0.0,
        port_file=str(tmp_path / "fleet" / PORT_FILE_NAME),
    )
    srv.start()
    yield srv, jobs, tmp_path
    srv.stop()
    for j in jobs:
        try:
            j.stop()
        except Exception:
            pass


def test_port_file_handshake(fleet):
    srv, _, tmp_path = fleet
    pf = tmp_path / "fleet" / PORT_FILE_NAME
    assert int(pf.read_text().strip()) == srv.port
    srv.stop()
    assert not pf.exists()


def test_all_endpoints_answer_and_carry_both_jobs(fleet):
    srv, _, _ = fleet
    status, prom, ctype = _get(srv.port, "/fleet/metrics")
    assert status == 200 and "version=0.0.4" in ctype
    assert 'tpu_ckpt_saves_total{job="job-a"} 1' in prom
    assert 'tpu_ckpt_saves_total{job="job-b"} 1' in prom
    assert "fleet:tpu_ckpt_saves_total 2" in prom
    doc = json.loads(_get(srv.port, "/fleet/goodput")[1])
    assert doc["schema"] == "tpu-fleet-goodput-1"
    assert [r["job"] for r in doc["jobs"]] == ["job-a", "job-b"]
    slo = json.loads(_get(srv.port, "/fleet/slo")[1])
    assert slo["schema"] == "tpu-fleet-slo-1" and len(slo["jobs"]) == 2
    inc = json.loads(_get(srv.port, "/fleet/incidents")[1])
    assert inc["schema"] == "tpu-fleet-incidents-1"
    hz = json.loads(_get(srv.port, "/fleet/hangz")[1])
    assert hz["schema"] == "tpu-fleet-hangz-1" and len(hz["jobs"]) == 2
    al = json.loads(_get(srv.port, "/fleet/alerts")[1])
    assert al["schema"] == "tpu-fleet-alerts-1" and len(al["jobs"]) == 2
    assert al["active"] == [] and al["unreachable"] == []
    snap = json.loads(_get(srv.port, "/fleet/snapshot")[1])
    assert snap["schema"] == "tpu-fleet-snapshot-1"
    hzdoc = json.loads(_get(srv.port, "/healthz")[1])
    assert hzdoc["healthy"] is True and hzdoc["jobs"] == 2


def test_killed_job_marks_unreachable_never_non_200(fleet):
    """The acceptance criterion: kill one job mid-scrape-loop — every fleet
    endpoint still answers 200, the dead job is an `unreachable` row."""
    srv, jobs, _ = fleet
    assert json.loads(_get(srv.port, "/fleet/goodput")[1])["fleet"]["reachable"] == 2
    _kill_job(jobs[0], srv.aggregator)
    for path in FLEET_ENDPOINTS:
        status, body, _ = _get(srv.port, path)
        assert status == 200, f"{path} degraded to {status} after a job death"
    doc = json.loads(_get(srv.port, "/fleet/goodput")[1])
    by_job = {r["job"]: r for r in doc["jobs"]}
    assert by_job["job-a"]["status"] == "unreachable"
    assert by_job["job-a"]["error"]
    assert by_job["job-b"]["status"] == "ok"
    slo = json.loads(_get(srv.port, "/fleet/slo")[1])
    assert slo["jobs"][0]["job"] == "job-a"  # the dead job leads the SLO page
    assert slo["jobs"][0]["status"] == "unreachable"
    prom = _get(srv.port, "/fleet/metrics")[0:2]
    assert prom[0] == 200 and 'tpu_fleet_scrape_errors_total{job="job-a"}' in prom[1]


def test_scrape_ttl_collapses_endpoint_storm(tmp_path):
    lease = JobLease(job="gone", url="http://127.0.0.1:1", pid=1)
    write_lease(str(tmp_path), lease)
    agg = FleetAggregator(str(tmp_path), timeout=0.2)
    calls = []
    orig = agg.scrape
    agg.scrape = lambda: (calls.append(1), orig())[1]
    srv = FleetServer(agg, port=0, scrape_ttl=30.0)
    srv.start()
    try:
        for path in FLEET_ENDPOINTS:
            assert _get(srv.port, path)[0] == 200
        assert len(calls) == 1, "endpoint storm did not collapse to one scrape"
    finally:
        srv.stop()


def test_unknown_path_is_404_with_directory(fleet):
    srv, _, _ = fleet
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(srv.port, "/nope")
    assert ei.value.code == 404
    doc = json.loads(ei.value.read())
    assert "/fleet/goodput" in doc["endpoints"]
    assert "/fleet/alerts" in doc["endpoints"]


def test_snapshot_roundtrips_through_the_cli(fleet, tmp_path, capsys):
    srv, jobs, _ = fleet
    _kill_job(jobs[1], srv.aggregator)
    path = str(tmp_path / "out" / "fleet.json")
    srv.write_snapshot(path)
    doc = json.load(open(path))
    assert doc["schema"] == "tpu-fleet-snapshot-1"
    # tpu-fleet renders all three views offline from the persisted snapshot.
    assert fleet_cli.main(["scoreboard", "--snapshot", path]) == 0
    out = capsys.readouterr().out
    assert "job-a" in out and "unreachable" in out
    assert fleet_cli.main(["slo", "--snapshot", path]) == 0
    assert "job-b" in capsys.readouterr().out
    assert fleet_cli.main(["incidents", "--snapshot", path, "--job", "job-a"]) == 0
    capsys.readouterr()
    # and refuses garbage
    bad = tmp_path / "bad.json"
    bad.write_text("{}")
    assert fleet_cli.main(["scoreboard", "--snapshot", str(bad)]) == 1
    assert fleet_cli.main(["scoreboard"]) == 2  # neither --snapshot nor --url


def test_cli_live_url(fleet, capsys):
    srv, _, _ = fleet
    assert fleet_cli.main(
        ["scoreboard", "--url", f"http://127.0.0.1:{srv.port}"]
    ) == 0
    assert "job-a" in capsys.readouterr().out


def test_fleetd_once_mode(tmp_path, capsys):
    from tpu_resiliency.tools import fleetd

    job = _start_job(tmp_path, "job-a")
    snap = str(tmp_path / "fleet.json")
    try:
        rc = fleetd.main([
            "--fleet-dir", str(tmp_path / "fleet"), "--once",
            "--snapshot", snap,
        ])
        assert rc == 0
        out = capsys.readouterr().out
        assert "1 job(s), 1 reachable" in out
        doc = json.load(open(snap))
        assert doc["schema"] == "tpu-fleet-snapshot-1"
        assert doc["goodput"]["jobs"][0]["job"] == "job-a"
    finally:
        job.stop()


def test_unexpired_scrape_failure_keeps_last_view(tmp_path):
    """A scrape that raises (fleet dir ripped out) degrades /healthz, keeps
    the last good view, and never downs a fleet endpoint."""
    agg = FleetAggregator(str(tmp_path / "fleet"))
    srv = FleetServer(agg, port=0, scrape_ttl=0.0)
    srv.start()
    try:
        assert _get(srv.port, "/fleet/goodput")[0] == 200

        def boom():
            raise RuntimeError("fleet dir gone")

        agg.scrape = boom
        status, body, _ = _get(srv.port, "/fleet/goodput")
        assert status == 200  # last good view served
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(srv.port, "/healthz")
        assert ei.value.code == 503
        assert "fleet dir gone" in json.loads(ei.value.read())["error"]
    finally:
        srv.stop()


def test_lease_heartbeat_keeps_job_live_and_stop_removes(tmp_path):
    """TelemetryServer registration: heartbeat refreshes survive a short TTL;
    a clean stop removes the lease immediately."""
    srv = TelemetryServer(
        port=0, fleet_dir=str(tmp_path / "fleet"), job="hb", lease_interval=0.1,
    )
    srv.start()
    lease_path = srv._lease.path
    try:
        hb0 = json.load(open(lease_path))["heartbeat_ts"]
        deadline = time.time() + 10
        while time.time() < deadline:
            if json.load(open(lease_path))["heartbeat_ts"] > hb0:
                break
            time.sleep(0.05)
        else:
            pytest.fail("lease heartbeat never refreshed")
    finally:
        srv.stop()
    assert not os.path.exists(lease_path)
