"""Fleet discovery leases: atomic writes, torn-read hardening, staleness
expiry, newest-wins identity under churn."""

import json
import os
import time

from tpu_resiliency.fleet.registry import (
    SCHEMA,
    JobLease,
    expire_stale,
    lease_path,
    live_leases,
    read_leases,
    remove_lease,
    write_lease,
)


def _lease(job="j1", pid=1234, url="http://127.0.0.1:1"):
    return JobLease(job=job, url=url, pid=pid, node_id="n0", started_at=10.0)


def test_write_read_roundtrip(tmp_path):
    d = str(tmp_path)
    path = write_lease(d, _lease())
    assert os.path.basename(path) == "job-j1-1234.json"
    leases = read_leases(d)
    assert len(leases) == 1
    got = leases[0]
    assert got.job == "j1" and got.url == "http://127.0.0.1:1"
    assert got.pid == 1234 and got.node_id == "n0"
    assert got.heartbeat_ts > 0 and got.path == path


def test_write_is_atomic_and_refresh_bumps_heartbeat(tmp_path):
    d = str(tmp_path)
    lease = _lease()
    write_lease(d, lease)
    hb1 = read_leases(d)[0].heartbeat_ts
    time.sleep(0.01)
    write_lease(d, lease)
    assert read_leases(d)[0].heartbeat_ts > hb1
    # no tmp droppings after an atomic rename
    assert [n for n in os.listdir(d) if ".tmp." in n] == []


def test_torn_and_foreign_files_are_skipped(tmp_path):
    d = str(tmp_path)
    write_lease(d, _lease())
    # torn JSON under a lease name
    (tmp_path / "job-torn-1.json").write_text('{"schema": "tpu-fleet-le')
    # wrong schema
    (tmp_path / "job-wrong-2.json").write_text(json.dumps({"schema": "nope"}))
    # missing required fields
    (tmp_path / "job-empty-3.json").write_text(json.dumps({"schema": SCHEMA}))
    # foreign files ignored entirely
    (tmp_path / "README.txt").write_text("not a lease")
    leases = read_leases(d)
    assert [lease.job for lease in leases] == ["j1"]


def test_live_leases_drops_stale(tmp_path):
    d = str(tmp_path)
    write_lease(d, _lease(job="fresh", pid=1))
    stale = _lease(job="stale", pid=2)
    write_lease(d, stale)
    # Backdate the stale job's heartbeat by rewriting its file directly.
    doc = stale.to_doc()
    doc["heartbeat_ts"] = time.time() - 100.0
    (tmp_path / os.path.basename(stale.path)).write_text(json.dumps(doc))
    live = live_leases(d, ttl=15.0)
    assert set(live) == {"fresh"}


def test_newest_heartbeat_wins_per_job(tmp_path):
    """Restart churn: two incarnations' lease files for one job yield ONE
    entry — the freshest heartbeat — never a duplicate scoreboard row."""
    d = str(tmp_path)
    old = _lease(job="j1", pid=100, url="http://old")
    write_lease(d, old)
    doc = old.to_doc()
    doc["heartbeat_ts"] = time.time() - 5.0
    (tmp_path / os.path.basename(old.path)).write_text(json.dumps(doc))
    write_lease(d, _lease(job="j1", pid=200, url="http://new"))
    live = live_leases(d, ttl=60.0)
    assert len(live) == 1
    assert live["j1"].url == "http://new" and live["j1"].pid == 200


def test_expire_stale_unlinks(tmp_path):
    d = str(tmp_path)
    write_lease(d, _lease(job="alive", pid=1))
    dead = _lease(job="dead", pid=2)
    write_lease(d, dead)
    doc = dead.to_doc()
    doc["heartbeat_ts"] = time.time() - 100.0
    (tmp_path / os.path.basename(dead.path)).write_text(json.dumps(doc))
    removed = expire_stale(d, ttl=15.0)
    assert removed == [dead.path]
    assert not os.path.exists(dead.path)
    assert [lease.job for lease in read_leases(d)] == ["alive"]


def test_remove_lease_and_missing_dir_are_benign(tmp_path):
    remove_lease(str(tmp_path / "nope.json"))  # no raise
    assert read_leases(str(tmp_path / "missing")) == []
    assert live_leases(str(tmp_path / "missing")) == {}


def test_lease_path_sanitizes_job_names(tmp_path):
    p = lease_path(str(tmp_path), "exp/../weird job", 7)
    assert os.path.dirname(p) == str(tmp_path)
    assert "/" not in os.path.basename(p).replace(".json", "").replace("job-", "", 1)
