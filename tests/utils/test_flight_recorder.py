"""Flight recorder: continuous segment persistence, rotation, fault flush,
collect() stitching, env wiring, and the crash-survival property (kill -9)."""

import json
import os
import signal
import subprocess
import sys
import textwrap
import time

import pytest

from tpu_resiliency.utils import events, flight_recorder


@pytest.fixture(autouse=True)
def clean():
    events.clear_sinks()
    saved = os.environ.pop(flight_recorder.FLIGHT_DIR_ENV, None)
    yield
    flight_recorder.uninstall()
    events.clear_sinks()
    if saved is not None:
        os.environ[flight_recorder.FLIGHT_DIR_ENV] = saved


def test_every_event_lands_on_disk_immediately(tmp_path):
    d = str(tmp_path / "fl")
    flight_recorder.install(d, capacity=100, install_handlers=False)
    events.record("test", "step_one", n=1)
    events.record("test", "step_two", n=2)
    # No flush, no close: the hot segment already holds both lines.
    dumps = flight_recorder.collect(d)
    assert len(dumps) == 1
    records = next(iter(dumps.values()))
    assert [r["kind"] for r in records] == ["step_one", "step_two"]
    assert records[1]["n"] == 2


def test_rotation_bounds_disk_and_keeps_recent_window(tmp_path):
    d = str(tmp_path / "fl")
    rec = flight_recorder.install(d, capacity=10, install_handlers=False)
    for i in range(35):
        events.record("test", "tick", i=i)
    names = sorted(os.listdir(d))
    # Exactly one hot + one prev segment — rotation replaces, never accumulates.
    assert len([n for n in names if n.endswith(".hot.jsonl")]) == 1
    assert len([n for n in names if n.endswith(".prev.jsonl")]) == 1
    records = next(iter(flight_recorder.collect(d).values()))
    # The newest events survive; the oldest rotated away.
    assert records[-1]["i"] == 34
    assert 10 <= len(records) <= 20
    assert rec is flight_recorder.get_recorder()


def test_flush_writes_consolidated_dump_with_reason(tmp_path):
    d = str(tmp_path / "fl")
    rec = flight_recorder.install(d, capacity=50, install_handlers=False)
    events.record("test", "before_death", x=1)
    path = rec.flush("signal:SIGTERM", detail="testing")
    assert path and os.path.exists(path)
    records = next(iter(flight_recorder.collect(d).values()))
    kinds = [r["kind"] for r in records]
    assert "before_death" in kinds
    marker = next(r for r in records if r["kind"] == "flight_flush")
    assert marker["reason"] == "signal:SIGTERM"
    assert marker["detail"] == "testing"


def test_flush_does_not_block_when_lock_held(tmp_path):
    # A SIGTERM can land while the main thread holds the ring lock inside
    # __call__; flush() runs on that same thread and must not deadlock — it
    # snapshots the ring without blocking and still writes the dump.
    d = str(tmp_path / "fl")
    rec = flight_recorder.install(d, capacity=50, install_handlers=False)
    events.record("test", "before_signal")
    assert rec._lock.acquire(blocking=False)
    try:
        path = rec.flush("signal:SIGTERM")
    finally:
        rec._lock.release()
    assert path and os.path.exists(path)
    records = next(iter(flight_recorder.collect(d).values()))
    kinds = [r["kind"] for r in records]
    assert "before_signal" in kinds and "flight_flush" in kinds


def test_events_after_flush_still_collected(tmp_path):
    d = str(tmp_path / "fl")
    rec = flight_recorder.install(d, capacity=50, install_handlers=False)
    events.record("test", "pre_flush")
    rec.flush("fn_exception")
    events.record("test", "post_flush")
    records = next(iter(flight_recorder.collect(d).values()))
    kinds = [r["kind"] for r in records]
    assert "pre_flush" in kinds and "post_flush" in kinds
    # The marker sits between them in ts order.
    assert kinds.index("pre_flush") < kinds.index("flight_flush")


def test_env_wiring_installs_lazily(tmp_path):
    d = str(tmp_path / "fl_env")
    os.environ[flight_recorder.FLIGHT_DIR_ENV] = d
    events.record("test", "wired_by_env")
    assert flight_recorder.get_recorder() is not None
    records = next(iter(flight_recorder.collect(d).values()))
    assert any(r["kind"] == "wired_by_env" for r in records)
    del os.environ[flight_recorder.FLIGHT_DIR_ENV]


def test_collect_ignores_garbage_and_missing_dir(tmp_path):
    assert flight_recorder.collect(str(tmp_path / "nope")) == {}
    d = tmp_path / "fl"
    d.mkdir()
    (d / "flight-3-99.jsonl").write_text('{"ts": 1.0, "kind": "ok"}\n{torn')
    records = flight_recorder.collect(str(d))["3-99"]
    assert [r["kind"] for r in records] == ["ok"]


_KILLED = textwrap.dedent(
    """
    import os, sys, time
    from tpu_resiliency.utils import events
    for i in range(20):
        events.record("worker", "train_step", step=i)
    with open(sys.argv[1], "w") as f:
        f.write("ready")
    time.sleep(60)   # parked: the parent kill -9s us here
    """
)


def test_sigkill_still_leaves_a_dump(tmp_path):
    """The crash-survival property: kill -9 is uncatchable, so the dump must
    already be on disk when it lands."""
    d = str(tmp_path / "fl")
    script = tmp_path / "victim.py"
    script.write_text(_KILLED)
    ready = str(tmp_path / "ready")
    env = dict(os.environ)
    env.update({
        flight_recorder.FLIGHT_DIR_ENV: d,
        "RANK": "7",
        "JAX_PLATFORMS": "cpu",
    })
    proc = subprocess.Popen([sys.executable, str(script), ready], env=env)
    deadline = time.monotonic() + 30
    while not os.path.exists(ready):
        assert time.monotonic() < deadline, "victim never became ready"
        assert proc.poll() is None, "victim died early"
        time.sleep(0.05)
    os.kill(proc.pid, signal.SIGKILL)
    proc.wait(timeout=10)
    dumps = flight_recorder.collect(d)
    ident = next(iter(dumps))
    assert ident.startswith("7-")
    kinds = [r["kind"] for r in dumps[ident]]
    assert kinds.count("train_step") == 20
    # No flush marker: the process died without warning — segments only.
    assert "flight_flush" not in kinds


def test_sigterm_handler_flushes_and_still_dies(tmp_path):
    script = tmp_path / "victim.py"
    script.write_text(textwrap.dedent(
        """
        import os, sys, time
        from tpu_resiliency.utils import events
        events.record("worker", "about_to_hang")
        with open(sys.argv[1], "w") as f:
            f.write("ready")
        time.sleep(60)
        """
    ))
    d = str(tmp_path / "fl")
    ready = str(tmp_path / "ready")
    env = dict(os.environ)
    env.update({flight_recorder.FLIGHT_DIR_ENV: d, "JAX_PLATFORMS": "cpu"})
    proc = subprocess.Popen([sys.executable, str(script), ready], env=env)
    deadline = time.monotonic() + 30
    while not os.path.exists(ready):
        assert time.monotonic() < deadline
        time.sleep(0.05)
    os.kill(proc.pid, signal.SIGTERM)
    rc = proc.wait(timeout=10)
    assert rc != 0  # the chained handler re-raised the default disposition
    records = next(iter(flight_recorder.collect(d).values()))
    marker = [r for r in records if r["kind"] == "flight_flush"]
    assert marker and marker[0]["reason"] == "signal:SIGTERM"


def test_reinstall_replaces_and_uninstall_detaches(tmp_path):
    d1, d2 = str(tmp_path / "a"), str(tmp_path / "b")
    flight_recorder.install(d1, install_handlers=False)
    flight_recorder.install(d2, install_handlers=False)
    events.record("test", "after_reinstall")
    assert not flight_recorder.collect(d1)
    assert flight_recorder.collect(d2)
    flight_recorder.uninstall()
    events.record("test", "after_uninstall")
    records = next(iter(flight_recorder.collect(d2).values()))
    assert all(r["kind"] != "after_uninstall" for r in records)
