"""The events-summary CLI: timeline rendering, kind filter, summary counts."""

import io
import json
import time

from tpu_resiliency.tools import events_summary


def _write_events(path, rows):
    t0 = time.time()
    with open(path, "w") as f:
        for dt, source, kind, payload in rows:
            f.write(
                json.dumps(
                    {"ts": t0 + dt, "source": source, "kind": kind, "pid": 1,
                     "rank": payload.pop("_rank", None), **payload}
                )
                + "\n"
            )


def test_timeline_and_summary(tmp_path):
    path = str(tmp_path / "ev.jsonl")
    _write_events(
        path,
        [
            (0.0, "launcher", "rendezvous_round",
             {"round": 0, "world_size": 2, "active": ["a"], "spares": []}),
            (1.0, "telemetry", "straggler_report",
             {"step": 100, "perf_scores": {"0": 1.0, "1": 0.4},
              "stragglers_by_perf": [1], "stragglers_by_section": {}}),
            (2.0, "launcher", "worker_failed",
             {"global_rank": 1, "exitcode": -9, "detail": "rank 1 exit -9"}),
            (2.5, "launcher", "worker_promoted",
             {"round": 1, "global_rank": 1, "worker_pid": 4242}),
            (3.0, "inprocess", "restart_signalled",
             {"iteration": 0, "initial_rank": 0, "_rank": 0}),
            (4.0, "custom", "my_new_kind", {"answer": 42}),
        ],
    )
    out = io.StringIO()
    events_summary.summarize(events_summary.read_events(path), out=out)
    text = out.getvalue()
    # Timeline lines render per-kind phrases with relative timestamps.
    assert "t+    0.000s [launcher] rendezvous_round: round 0: world=2" in text
    assert "STRAGGLERS by perf [1]" in text
    assert "rank 1 failed: rank 1 exit -9" in text
    assert "warm spare promoted -> rank 1 (round 1, pid 4242)" in text
    assert "[inprocess r0] restart_signalled: iteration 0 restarting (initial_rank 0)" in text
    # Unknown kinds still print (raw payload), never crash.
    assert "my_new_kind: answer=42" in text
    # Summary footer.
    assert "6 events over 4.0s" in text
    assert "worker failures: 1" in text
    assert "warm-spare promotions: 1" in text
    assert "straggler reports: 1" in text
    assert "other: {'my_new_kind': 1}" in text


def test_kind_filter_and_no_timeline(tmp_path):
    path = str(tmp_path / "ev.jsonl")
    _write_events(
        path,
        [
            (0.0, "launcher", "rendezvous_round",
             {"round": 0, "world_size": 1, "active": ["a"], "spares": []}),
            (1.0, "launcher", "worker_failed",
             {"global_rank": 0, "exitcode": 1, "detail": "rank 0 exit 1"}),
        ],
    )
    out = io.StringIO()
    events_summary.summarize(
        events_summary.read_events(path), out=out, kind="worker_failed"
    )
    text = out.getvalue()
    assert "worker_failed" in text and "rendezvous_round:" not in text
    # The footer counts the filtered slice — what the timeline shows is what
    # the counts summarize.
    assert "rendezvous rounds" not in text
    assert "worker failures: 1" in text
    assert "1 events" in text

    # Comma-separated kinds widen the slice; the footer follows.
    out_multi = io.StringIO()
    events_summary.summarize(
        events_summary.read_events(path), out=out_multi,
        kind="worker_failed,rendezvous_round",
    )
    multi = out_multi.getvalue()
    assert "worker_failed" in multi and "rendezvous_round" in multi
    assert "rendezvous rounds: 1" in multi and "worker failures: 1" in multi
    assert "2 events" in multi

    out2 = io.StringIO()
    events_summary.summarize(
        events_summary.read_events(path), out=out2, timeline=False
    )
    assert "t+" not in out2.getvalue()
    assert "worker failures: 1" in out2.getvalue()


def _write_incident_stream(path):
    """Two 'runs' on one stream: trace A faults at t+10..t+20, trace B is a
    different job sharing the file."""
    import json
    import time

    t0 = time.time()
    rows = [
        (0.0, "launcher", "rendezvous_round", "A", {"round": 0, "world_size": 1}),
        (10.0, "launcher", "worker_failed", "A",
         {"global_rank": 0, "exitcode": -9, "detail": "rank 0 exit -9"}),
        (12.0, "launcher", "restart_requested", "A", {"reason": "rank 0 died"}),
        (15.0, "launcher", "rendezvous_round", "B", {"round": 0, "world_size": 1}),
        (20.0, "launcher", "round_succeeded", "A", {"round": 1}),
        (30.0, "ft", "training_finished", "A", {"step": 5}),
    ]
    with open(path, "w") as f:
        for dt, source, kind, trace, payload in rows:
            f.write(json.dumps(
                {"ts": t0 + dt, "source": source, "kind": kind, "pid": 1,
                 "trace_id": trace, **payload}
            ) + "\n")
    return t0


class TestSliceFilters:
    """--since/--until/--trace: slice the stream to one incident without grep."""

    def test_relative_window_slices_timeline_and_footer(self, tmp_path):
        path = str(tmp_path / "ev.jsonl")
        _write_incident_stream(path)
        out = io.StringIO()
        records = events_summary.read_events(path)
        t0 = min(r["ts"] for r in records)
        keep = events_summary.make_filter("+9", "+21", None, t0)
        events_summary.summarize(records, out=out, keep=keep)
        text = out.getvalue()
        assert "worker_failed" in text and "restart_requested" in text
        assert "round_succeeded" in text
        assert "training_finished" not in text  # t+30 is outside
        assert "4 events" in text  # footer counts the slice, not the stream
        # t+ offsets stay anchored to the FULL stream's first event.
        assert "t+   10.000s" in text

    def test_absolute_epoch_bounds(self, tmp_path):
        path = str(tmp_path / "ev.jsonl")
        t0 = _write_incident_stream(path)
        records = events_summary.read_events(path)
        keep = events_summary.make_filter(str(t0 + 9), str(t0 + 13), None, t0)
        out = io.StringIO()
        events_summary.summarize(records, out=out, keep=keep)
        text = out.getvalue()
        assert "worker_failed" in text and "restart_requested" in text
        assert "round_succeeded" not in text

    def test_trace_filter_drops_the_other_run(self, tmp_path):
        path = str(tmp_path / "ev.jsonl")
        _write_incident_stream(path)
        records = events_summary.read_events(path)
        keep = events_summary.make_filter(None, None, "A", 0.0)
        out = io.StringIO()
        events_summary.summarize(records, out=out, keep=keep)
        text = out.getvalue()
        assert "5 events" in text  # B's rendezvous_round gone
        assert text.count("rendezvous_round:") == 1

    def test_iso_spec_parses(self):
        import datetime

        ts, rel = events_summary.parse_when("2026-08-04T12:00:00")
        assert not rel
        assert ts == datetime.datetime(2026, 8, 4, 12, 0).timestamp()
        assert events_summary.parse_when("+5.5") == (5.5, True)
        assert events_summary.parse_when("1700000000.25") == (1700000000.25, False)

    def test_cli_flags_end_to_end(self, tmp_path, capsys):
        path = str(tmp_path / "ev.jsonl")
        _write_incident_stream(path)
        assert events_summary.main([path, "--since", "+9", "--until", "+21",
                                    "--trace", "A"]) == 0
        out = capsys.readouterr().out
        assert "worker_failed" in out and "training_finished" not in out
        # A typo'd bound fails the invocation, not silently shows everything.
        assert events_summary.main([path, "--since", "yesterdayish"]) == 2
        assert "cannot parse time" in capsys.readouterr().err

    def test_empty_slice_says_so(self, tmp_path, capsys):
        path = str(tmp_path / "ev.jsonl")
        _write_incident_stream(path)
        assert events_summary.main([path, "--since", "+1000"]) == 0
        assert "no events in the selected slice" in capsys.readouterr().out

    def test_job_filter_slices_a_fleet_stream(self, tmp_path, capsys):
        """--job: records stamped with the fleet job identity (launcher
        --fleet-dir) slice back to one job; unstamped records drop out of
        any job's slice; composes with --kind."""
        import json as _json
        import time as _time

        path = str(tmp_path / "ev.jsonl")
        t0 = _time.time()
        with open(path, "w") as f:
            for i, (job, kind) in enumerate((
                ("a", "worker_failed"), ("b", "worker_failed"),
                ("a", "rendezvous_round"), (None, "worker_failed"),
            )):
                rec = {"ts": t0 + i, "source": "launcher", "kind": kind,
                       "pid": 1, "global_rank": 0}
                if job is not None:
                    rec["job"] = job
                f.write(_json.dumps(rec) + "\n")
        assert events_summary.main([path, "--job", "a"]) == 0
        out = capsys.readouterr().out
        assert "2 events" in out
        assert "worker failures: 1" in out
        assert events_summary.main(
            [path, "--job", "a", "--kind", "worker_failed"]
        ) == 0
        assert "1 events" in capsys.readouterr().out
        # The job identity is envelope, not payload: never printed as job=.
        assert events_summary.main([path, "--job", "b"]) == 0
        assert "job=b" not in capsys.readouterr().out


def test_cli_main(tmp_path, capsys):
    path = str(tmp_path / "ev.jsonl")
    _write_events(path, [(0.0, "ft", "training_finished", {"step": 30})])
    assert events_summary.main([path]) == 0
    assert "training finished: 1" in capsys.readouterr().out
    assert events_summary.main([str(tmp_path / "missing.jsonl")]) == 1


def test_cli_fails_visibly_on_unreadable_path(tmp_path, capsys):
    # A directory passes os.path.exists but cannot be read as a stream.
    assert events_summary.main([str(tmp_path)]) == 1
    assert "cannot read events file" in capsys.readouterr().err


def test_big_output_through_closed_pipe_exits_clean(tmp_path):
    """`tool big.jsonl | head -1` with >8KB of output: the write that dies on
    the closed pipe is the interpreter-exit flush, which must be absorbed by
    pipe_safe (rc 0, no 'Exception ignored' noise on stderr)."""
    import subprocess
    import sys

    path = str(tmp_path / "big.jsonl")
    _write_events(
        path,
        [(float(i), "x", f"k{i % 7}", {"data": "y" * 40}) for i in range(2000)],
    )
    r = subprocess.run(
        f"{sys.executable} -m tpu_resiliency.tools.events_summary {path} | head -1",
        shell=True,
        capture_output=True,
        text=True,
    )
    assert r.returncode == 0, r.stderr
    assert "BrokenPipe" not in r.stderr and "Exception ignored" not in r.stderr
    assert r.stdout.startswith("t+")


def test_iter_new_records_tails_a_growing_file(tmp_path):
    """The --follow reader yields records as a writer appends them, survives
    the file not existing yet, and reassembles torn trailing lines."""
    import json
    import threading
    import time

    path = str(tmp_path / "grow.jsonl")
    stop = threading.Event()
    got = []

    def reader():
        for rec in events_summary.iter_new_records(path, poll=0.02, stop=stop):
            got.append(rec)

    t = threading.Thread(target=reader, daemon=True)
    t.start()
    time.sleep(0.1)  # reader polling a nonexistent file must not crash

    def ev(i):
        return json.dumps(
            {"ts": float(i), "source": "x", "kind": "k", "pid": 1, "i": i}
        )

    with open(path, "a") as f:
        f.write(ev(0) + "\n")
        f.flush()
        time.sleep(0.1)
        # Torn write: half a line now, the rest (plus another event) later.
        whole = ev(1) + "\n"
        f.write(whole[:10])
        f.flush()
        time.sleep(0.1)
        assert [r["i"] for r in got] == [0], "torn line must not be yielded"
        f.write(whole[10:] + ev(2) + "\n")
        f.flush()

    deadline = time.time() + 5
    while len(got) < 3 and time.time() < deadline:
        time.sleep(0.02)
    stop.set()
    t.join(timeout=5)
    assert [r["i"] for r in got] == [0, 1, 2]


def test_follow_fails_visibly_on_unreadable_path(tmp_path, capsys):
    """A directory (or permission-denied path) must error out, not hang as if
    waiting for a launcher; only a MISSING file is the wait state."""
    assert events_summary._follow(str(tmp_path), kind=None) == 1
    assert "cannot follow events file" in capsys.readouterr().err


def test_follow_through_closed_pipe_exits_clean(tmp_path):
    """`--follow | head -2` on a pre-populated stream: head's exit must end
    the follower cleanly (rc 0, no BrokenPipe noise), like batch mode."""
    import json
    import subprocess
    import sys
    import time

    path = str(tmp_path / "f.jsonl")
    with open(path, "w") as f:
        for i in range(50):
            f.write(json.dumps({"ts": float(i), "source": "x", "kind": "k", "pid": 1}) + "\n")
    p = subprocess.Popen(
        f"{sys.executable} -m tpu_resiliency.tools.events_summary {path} --follow | head -2",
        shell=True,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
    )
    try:
        out, err = p.communicate(timeout=30)
    except subprocess.TimeoutExpired:
        p.kill()
        raise AssertionError("follower did not exit after the pipe closed")
    assert p.returncode == 0, err
    assert "BrokenPipe" not in err and "Exception ignored" not in err
    assert out.count("\n") == 2


def test_follow_reader_resets_on_truncation(tmp_path):
    """A recreated/truncated stream (new launcher run reusing the path) must
    be re-read from the top, tail -f style, not silently stall."""
    import json
    import threading
    import time

    path = str(tmp_path / "t.jsonl")

    def ev(i):
        return json.dumps({"ts": float(i), "source": "x", "kind": "k", "pid": 1, "i": i}) + "\n"

    with open(path, "w") as f:
        f.write(ev(0) + ev(1))
    stop = threading.Event()
    got = []

    def reader():
        for rec in events_summary.iter_new_records(path, poll=0.02, stop=stop):
            got.append(rec["i"])

    t = threading.Thread(target=reader, daemon=True)
    t.start()
    deadline = time.time() + 5
    while got != [0, 1] and time.time() < deadline:
        time.sleep(0.02)
    assert got == [0, 1]
    with open(path, "w") as f:  # truncating rewrite: shorter than old offset
        f.write(ev(7))
    deadline = time.time() + 5
    while got != [0, 1, 7] and time.time() < deadline:
        time.sleep(0.02)
    stop.set()
    t.join(timeout=5)
    assert got == [0, 1, 7]


def test_iter_new_records_detects_recreated_file_by_inode(tmp_path):
    """tail -F semantics: a NEW events file at the same path (next launcher
    run) that has already grown PAST the old byte offset must be read from
    its top — size-shrink detection alone would resume mid-file."""
    import json
    import os
    import threading
    import time

    path = str(tmp_path / "rotate.jsonl")

    def ev(i, pad=0):
        return json.dumps(
            {"ts": float(i), "source": "x", "kind": "k", "pid": 1, "i": i,
             "pad": "y" * pad}
        )

    with open(path, "w") as f:
        f.write(ev(0) + "\n")  # short old file
    stop = threading.Event()
    got = []

    def reader():
        for rec in events_summary.iter_new_records(path, poll=0.02, stop=stop):
            got.append(rec["i"])

    t = threading.Thread(target=reader, daemon=True)
    t.start()
    deadline = time.time() + 5
    while got != [0] and time.time() < deadline:
        time.sleep(0.02)
    assert got == [0]
    # Recreate atomically with a BIGGER file (padded records): its size
    # exceeds the reader's offset, so only the inode change reveals the swap.
    tmp = path + ".new"
    with open(tmp, "w") as f:
        f.write(ev(10, pad=200) + "\n" + ev(11, pad=200) + "\n")
    os.replace(tmp, path)
    deadline = time.time() + 5
    while got != [0, 10, 11] and time.time() < deadline:
        time.sleep(0.02)
    stop.set()
    t.join(timeout=5)
    assert got == [0, 10, 11], "new run's head was skipped (offset not reset)"


def test_truncated_by_head_exits_141(tmp_path):
    """SIGPIPE convention: a pipe-truncated run exits 141, a complete one 0 —
    scripts can tell the difference."""
    import json
    import subprocess
    import sys

    path = str(tmp_path / "big.jsonl")
    _write_events(
        path,
        [(float(i), "x", f"k{i % 7}", {"data": "y" * 40}) for i in range(2000)],
    )
    r = subprocess.run(
        ["bash", "-c",
         f"set -o pipefail; {sys.executable} -m tpu_resiliency.tools.events_summary"
         f" {path} | head -1"],
        capture_output=True, text=True, timeout=60,
    )
    assert r.returncode == 141, (r.returncode, r.stderr)
    assert "Exception ignored" not in r.stderr


def test_alert_transitions_render_and_count(tmp_path):
    path = str(tmp_path / "ev.jsonl")
    _write_events(
        path,
        [
            (0.0, "watchtower", "alert_fired",
             {"rule": "step_anomaly", "severity": "page",
              "detail": "z=12.3 over 600s"}),
            (9.0, "watchtower", "alert_resolved",
             {"rule": "step_anomaly", "severity": "page", "duration_s": 9.0,
              "detail": "back under z_max"}),
            (10.0, "watchtower", "alert_fired",
             {"rule": "goodput_burn", "severity": "page"}),
        ],
    )
    out = io.StringIO()
    events_summary.summarize(events_summary.read_events(path), out=out)
    text = out.getvalue()
    assert "rule=step_anomaly sev=page FIRING: z=12.3 over 600s" in text
    assert "rule=step_anomaly sev=page resolved for 9s: back under z_max" in text
    assert "rule=goodput_burn sev=page FIRING" in text  # detail optional
    assert "watchtower alerts fired: 2" in text
    assert "watchtower alerts resolved: 1" in text


def test_store_ha_events_render_and_count(tmp_path):
    path = str(tmp_path / "ev.jsonl")
    _write_events(
        path,
        [
            (0.0, "store", "store_failover",
             {"shard": 1, "op": "get", "outcome": "read",
              "endpoint": "10.0.0.2:7777", "successor": 2}),
            (0.5, "store", "store_failover",
             {"shard": 1, "op": "barrier", "outcome": "barrier",
              "endpoint": "10.0.0.2:7777", "successor": 2}),
            (2.0, "store", "shard_epoch",
             {"epoch": 3, "nshards": 4, "outcome": "migrating"}),
            (4.0, "store", "shard_epoch",
             {"epoch": 3, "nshards": 4, "outcome": "settled",
              "migrated": 120}),
        ],
    )
    out = io.StringIO()
    events_summary.summarize(events_summary.read_events(path), out=out)
    text = out.getvalue()
    assert "shard 1 (10.0.0.2:7777) get: read → successor shard 2" in text
    assert "shard 1 (10.0.0.2:7777) barrier: barrier → successor shard 2" in text
    assert "epoch 3 (4 shards): migrating" in text
    assert "epoch 3 (4 shards): settled, 120 keys migrated" in text
    assert "store shard failovers: 2" in text
    assert "store shard-map epoch transitions: 2" in text
