"""Metrics registry: primitives, quantiles, Prometheus exposition, events bridge."""

import json
import math
import os
import re
import threading

import pytest

from tpu_resiliency.utils import events
from tpu_resiliency.utils.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    MetricsSink,
    aggregate,
    observe_record,
)


@pytest.fixture(autouse=True)
def clean_sinks():
    events.clear_sinks()
    old = os.environ.pop(events.EVENTS_FILE_ENV, None)
    yield
    events.clear_sinks()
    if old is not None:
        os.environ[events.EVENTS_FILE_ENV] = old


def test_counter_and_gauge():
    c = Counter()
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    with pytest.raises(ValueError):
        c.inc(-1)
    g = Gauge()
    g.set(7)
    g.inc(3)
    g.dec()
    assert g.value == 9.0


def test_histogram_quantiles_exact_below_reservoir():
    h = Histogram()
    for v in range(1, 101):  # 0.01 .. 1.00
        h.observe(v / 100)
    assert h.count == 100 and abs(h.sum - 50.5) < 1e-9
    assert abs(h.quantile(0.5) - 0.50) < 1e-9
    assert abs(h.quantile(0.95) - 0.95) < 1e-9
    assert abs(h.quantile(1.0) - 1.00) < 1e-9
    assert abs(h.quantile(0.0) - 0.01) < 1e-9
    assert math.isnan(Histogram().quantile(0.5))
    with pytest.raises(ValueError):
        h.quantile(1.5)


def test_histogram_buckets_are_cumulative_in_exposition():
    reg = MetricsRegistry()
    h = reg.histogram("lat_seconds", "latency", (0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0, 50.0):
        h.observe(v)
    text = reg.to_prometheus()
    assert 'lat_seconds_bucket{le="0.1"} 1' in text
    assert 'lat_seconds_bucket{le="1"} 3' in text
    assert 'lat_seconds_bucket{le="10"} 4' in text
    assert 'lat_seconds_bucket{le="+Inf"} 5' in text
    assert "lat_seconds_count 5" in text
    assert "# TYPE lat_seconds histogram" in text


def test_registry_get_or_create_and_type_conflict():
    reg = MetricsRegistry()
    a = reg.counter("x_total", kind="a")
    assert reg.counter("x_total", kind="a") is a  # same series
    assert reg.counter("x_total", kind="b") is not a  # same family, new series
    with pytest.raises(ValueError):
        reg.gauge("x_total")  # one family, one type


def test_prometheus_format_is_parseable():
    """Every sample line must match the exposition grammar (name{labels} value)."""
    reg = MetricsRegistry()
    reg.counter("tpu_restarts_total", "restarts", layer="injob").inc(2)
    reg.gauge("tpu_world_size").set(8)
    reg.histogram("tpu_span_seconds", span="rendezvous.round").observe(0.25)
    line_re = re.compile(
        r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[a-zA-Z0-9_]+=\"[^\"]*\""
        r"(,[a-zA-Z0-9_]+=\"[^\"]*\")*\})? [0-9eE.+-]+$|^\+Inf$"
    )
    for line in reg.to_prometheus().splitlines():
        if line.startswith("#") or not line:
            continue
        assert line_re.match(line.replace("+Inf", "Inf")), line


def test_metric_name_sanitized():
    reg = MetricsRegistry()
    reg.counter("weird-name.total").inc()
    assert "weird_name_total 1" in reg.to_prometheus()


def test_label_values_escaped_per_exposition_format():
    """Regression: a backslash, double-quote, or newline in a label value
    (peer addresses, file paths) must render as valid 0.0.4 text — escaped,
    never raw."""
    reg = MetricsRegistry()
    reg.counter("x_total", path="C:\\tmp\\f").inc()
    reg.counter("x_total", peer='he said "hi"').inc()
    reg.counter("x_total", detail="line1\nline2").inc()
    text = reg.to_prometheus()
    assert 'path="C:\\\\tmp\\\\f"' in text
    assert 'peer="he said \\"hi\\""' in text
    assert 'detail="line1\\nline2"' in text
    # No sample line may span two physical lines.
    for line in text.splitlines():
        if line.startswith("#") or not line:
            continue
        assert line.count('"') % 2 == 0, line
    # HELP text gets backslash/newline escaping too.
    reg2 = MetricsRegistry()
    reg2.counter("y_total", "multi\nline \\help").inc()
    help_line = next(
        ln for ln in reg2.to_prometheus().splitlines() if ln.startswith("# HELP")
    )
    assert help_line == "# HELP y_total multi\\nline \\\\help"


def test_write_json_is_strict_json(tmp_path):
    """Snapshots are restricted to plain JSON types: NaN quantiles become
    null (not a repr string, not a bare NaN token) and the document parses
    under a strict-JSON reader."""
    reg = MetricsRegistry()
    reg.counter("c_total").inc(2)
    reg.histogram("h_seconds")  # zero observations -> NaN quantiles
    reg.gauge("g").set(1.5)
    path = str(tmp_path / "m.json")
    reg.write_json(path)

    def no_constants(name):
        raise AssertionError(f"non-JSON constant {name} leaked into snapshot")

    doc = json.loads(open(path).read(), parse_constant=no_constants)
    h = doc["metrics"]["h_seconds"][0]
    assert h["p50"] is None and h["count"] == 0
    assert doc["metrics"]["c_total"][0]["value"] == 2
    # Round-trip: the parsed document is byte-equivalent snapshot content.
    assert json.loads(json.dumps(doc)) == doc


def test_snapshot_drops_non_coercible_values():
    from tpu_resiliency.utils.metrics import _plain_json

    class Weird:
        pass

    doc = _plain_json({"ok": 1, "bad": Weird(), "nan": float("nan"),
                       "inf": float("inf"), "np_like": True})
    assert doc == {"ok": 1, "bad": None, "nan": None, "inf": None,
                   "np_like": True}


def test_iteration_start_feeds_step_histogram():
    """The satellite: iteration_start deltas land in tpu_step_seconds — but
    only strictly-consecutive iterations within the gap cap (a repeat after
    an in-process restart or a multi-minute stall is downtime, not a step)."""
    from tpu_resiliency.utils.metrics import STEP_GAP_MAX_S

    reg = MetricsRegistry()
    t0 = 1000.0
    recs = [
        {"kind": "iteration_start", "iteration": 0, "ts": t0, "pid": 7},
        {"kind": "iteration_start", "iteration": 1, "ts": t0 + 0.5, "pid": 7},
        {"kind": "iteration_start", "iteration": 2, "ts": t0 + 1.0, "pid": 7},
        # same iteration again (in-process restart): not a step
        {"kind": "iteration_start", "iteration": 2, "ts": t0 + 9.0, "pid": 7},
        # consecutive but beyond the gap cap: not a step
        {"kind": "iteration_start", "iteration": 3,
         "ts": t0 + 9.0 + STEP_GAP_MAX_S + 1, "pid": 7},
        # a different pid has its own chain
        {"kind": "iteration_start", "iteration": 0, "ts": t0, "pid": 8},
        {"kind": "iteration_start", "iteration": 1, "ts": t0 + 0.25, "pid": 8},
    ]
    aggregate(recs, reg)
    hists = reg.histograms("tpu_step_seconds")
    assert len(hists) == 1
    h = next(iter(hists.values()))
    assert h.count == 3  # 2 steps from pid 7 + 1 from pid 8
    assert abs(h.sum - 1.25) < 1e-9
    # Live sink parity: the same records through MetricsSink agree.
    live = MetricsRegistry()
    for r in recs:
        from tpu_resiliency.utils.metrics import observe_record as orec
        orec(r, live)
    lh = next(iter(live.histograms("tpu_step_seconds").values()))
    assert lh.count == h.count and lh.bucket_counts == h.bucket_counts


def test_snapshot_and_write_json(tmp_path):
    reg = MetricsRegistry()
    reg.counter("c_total").inc(3)
    reg.histogram("h_seconds").observe(1.0)
    path = str(tmp_path / "sub" / "m.json")
    reg.write_json(path)
    doc = json.load(open(path))
    m = doc["metrics"]
    assert m["c_total"][0]["value"] == 3
    assert m["h_seconds"][0]["count"] == 1
    assert m["h_seconds"][0]["p95"] == 1.0
    assert not [f for f in os.listdir(tmp_path / "sub") if ".tmp." in f]


def test_counter_thread_safety():
    c = Counter()

    def work():
        for _ in range(10_000):
            c.inc()

    ts = [threading.Thread(target=work) for _ in range(8)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert c.value == 80_000


def test_observe_record_mapping():
    reg = MetricsRegistry()
    recs = [
        {"kind": "rendezvous_round", "round": 1, "world_size": 4},
        {"kind": "restart_requested"},
        {"kind": "restart_signalled"},
        {"kind": "worker_failed"},
        {"kind": "hang_detected"},
        {"kind": "ckpt_saved", "bytes": 1024},
        {"kind": "timing", "name": "ckpt.save.write", "duration_s": 0.2, "ok": True},
        {"kind": "timing", "name": "ckpt.save.write", "duration_s": 0.4, "ok": False},
        {"kind": "span_end", "span": "rendezvous.round", "duration_s": 1.5, "ok": True},
        {"kind": "unmapped_novelty"},
        {"no_kind": True},
    ]
    aggregate(recs, reg)
    snap = reg.snapshot()["metrics"]
    total = sum(e["value"] for e in snap["tpu_events_total"])
    assert total == 10  # the kindless record is skipped, the novel kind counted
    by_layer = {
        tuple(sorted(e["labels"].items())): e["value"]
        for e in snap["tpu_restarts_total"]
    }
    assert by_layer == {(("layer", "injob"),): 1, (("layer", "inprocess"),): 1}
    assert snap["tpu_worker_failures_total"][0]["value"] == 1
    assert snap["tpu_rank_terminations_total"][0]["labels"] == {"cause": "hang"}
    assert snap["tpu_ckpt_saves_total"][0]["value"] == 1
    h = reg.histograms("tpu_timing_seconds")[(("name", "ckpt.save.write"),)]
    assert h.count == 2
    assert snap["tpu_timing_failures_total"][0]["value"] == 1
    rdzv = reg.histograms("tpu_span_seconds")[(("span", "rendezvous.round"),)]
    assert rdzv.quantile(0.95) == 1.5
    assert reg.gauge("tpu_world_size").value == 4


def test_metrics_sink_bridges_live_records(tmp_path):
    """One record() call feeds the JSONL stream AND the registry."""
    reg = MetricsRegistry()
    jsonl = str(tmp_path / "ev.jsonl")
    events.add_sink(events.JsonlSink(jsonl))
    events.add_sink(MetricsSink(reg, json_path=str(tmp_path / "m.json"),
                                snapshot_interval=0.0))
    events.record("launcher", "restart_requested", reason="test")
    events.record("checkpoint", "timing", name="ckpt.load", duration_s=0.1, ok=True)
    # payload keys colliding with the envelope get the same p_-rename as JSONL
    events.record("x", "y", ts=-1, pid=-1)
    recs = events.read_events(jsonl)
    assert len(recs) == 3
    assert recs[2]["p_ts"] == -1 and recs[2]["ts"] != -1
    snap = reg.snapshot()["metrics"]
    assert snap["tpu_restarts_total"][0]["value"] == 1
    kinds = {e["labels"]["kind"] for e in snap["tpu_events_total"]}
    assert kinds == {"restart_requested", "timing", "y"}
    # The piggybacked snapshot file landed and parses.
    doc = json.load(open(tmp_path / "m.json"))
    assert "tpu_events_total" in doc["metrics"]


def test_aggregate_matches_sink(tmp_path):
    """Live-bridged and post-hoc-aggregated registries agree on the same run."""
    jsonl = str(tmp_path / "ev.jsonl")
    live = MetricsRegistry()
    events.add_sink(events.JsonlSink(jsonl))
    events.add_sink(MetricsSink(live))
    for i in range(5):
        events.record("launcher", "rendezvous_round", round=i, world_size=2)
    events.record("launcher", "worker_failed", global_rank=0, exitcode=3)
    post = aggregate(events.read_events(jsonl))
    for reg in (live, post):
        snap = reg.snapshot()["metrics"]
        assert snap["tpu_rendezvous_rounds_total"][0]["value"] == 5
        assert snap["tpu_worker_failures_total"][0]["value"] == 1


def test_env_var_wires_metrics_bridge(tmp_path, monkeypatch):
    """$TPU_RESILIENCY_METRICS_FILE attaches a MetricsSink lazily, with the
    pid inserted so sibling processes never clobber each other's snapshot."""
    mpath = tmp_path / "m.json"
    monkeypatch.setenv(events.METRICS_FILE_ENV, str(mpath))
    events.record("launcher", "worker_failed", global_rank=0)
    expect = tmp_path / f"m.{os.getpid()}.json"
    assert expect.exists(), os.listdir(tmp_path)
    doc = json.load(open(expect))
    vals = [e["value"] for e in doc["metrics"]["tpu_worker_failures_total"]]
    assert vals and vals[0] >= 1


def test_step_gap_cap_is_env_tunable(monkeypatch):
    """$TPU_RESILIENCY_STEP_GAP_MAX retunes the consecutive-step cap per
    workload; garbage or non-positive values fall back to the 300s default
    rather than taking metrics down."""
    from tpu_resiliency.utils.metrics import (
        STEP_GAP_ENV, STEP_GAP_MAX_S, step_gap_max_s,
    )

    monkeypatch.delenv(STEP_GAP_ENV, raising=False)
    assert step_gap_max_s() == STEP_GAP_MAX_S == 300.0
    monkeypatch.setenv(STEP_GAP_ENV, "5")
    assert step_gap_max_s() == 5.0
    for bad in ("zero-ish", "", "0", "-3"):
        monkeypatch.setenv(STEP_GAP_ENV, bad)
        assert step_gap_max_s() == STEP_GAP_MAX_S
    # The knob reaches the step histogram: a 10s gap is a step under the
    # default cap but downtime under a 5s cap.
    recs = [
        {"kind": "iteration_start", "iteration": 0, "ts": 100.0, "pid": 7},
        {"kind": "iteration_start", "iteration": 1, "ts": 110.0, "pid": 7},
    ]
    monkeypatch.setenv(STEP_GAP_ENV, "5")
    reg = MetricsRegistry()
    aggregate(recs, reg)
    assert not reg.histograms("tpu_step_seconds")
    monkeypatch.delenv(STEP_GAP_ENV)
    reg = MetricsRegistry()
    aggregate(recs, reg)
    assert next(iter(reg.histograms("tpu_step_seconds").values())).count == 1


def test_alert_transitions_feed_alert_metrics():
    """alert_fired/alert_resolved drive the pair the watchtower exports:
    a by-rule/severity fired counter and a net active-alerts gauge."""
    reg = MetricsRegistry()
    aggregate([
        {"kind": "alert_fired", "rule": "goodput_burn", "severity": "page"},
        {"kind": "alert_fired", "rule": "ckpt_staleness", "severity": "warn"},
        {"kind": "alert_resolved", "rule": "goodput_burn", "severity": "page",
         "duration_s": 12.0},
    ], reg)
    snap = reg.snapshot()["metrics"]
    fired = {
        tuple(sorted(e["labels"].items())): e["value"]
        for e in snap["tpu_alerts_total"]
    }
    assert fired == {
        (("rule", "goodput_burn"), ("severity", "page")): 1,
        (("rule", "ckpt_staleness"), ("severity", "warn")): 1,
    }
    assert reg.gauge("tpu_alerts_active").value == 1  # 2 fired - 1 resolved
    prom = reg.to_prometheus()
    assert 'tpu_alerts_total{rule="goodput_burn",severity="page"} 1' in prom
    assert "tpu_alerts_active 1" in prom
