"""Cross-process tracing: span pairing, parent chains, env propagation."""

import json
import os
import subprocess
import sys

import pytest

from tpu_resiliency.utils import events, tracing


@pytest.fixture(autouse=True)
def clean():
    events.clear_sinks()
    saved = {
        k: os.environ.pop(k, None)
        for k in (events.EVENTS_FILE_ENV, tracing.TRACE_ID_ENV, tracing.PARENT_SPAN_ENV)
    }
    yield
    events.clear_sinks()
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


def _sink(tmp_path, name="t.jsonl"):
    path = str(tmp_path / name)
    events.add_sink(events.JsonlSink(path))
    return path


def test_span_pair_shares_envelope_span_id(tmp_path):
    path = _sink(tmp_path)
    tracing.ensure_trace_id()
    with tracing.span("launcher", "launcher.round", round=3):
        pass
    begin, end = events.read_events(path)
    assert begin["kind"] == "span_begin" and end["kind"] == "span_end"
    assert begin["span"] == end["span"] == "launcher.round"
    assert begin["span_id"] == end["span_id"]  # the pairing key
    assert begin["round"] == 3
    assert end["ok"] is True and end["duration_s"] >= 0
    assert begin["trace_id"] == end["trace_id"] == tracing.trace_id()


def test_nested_spans_form_a_parent_chain(tmp_path):
    path = _sink(tmp_path)
    with tracing.span("a", "outer"):
        with tracing.span("a", "inner"):
            pass
    recs = events.read_events(path)
    outer_b, inner_b, inner_e, outer_e = recs
    assert outer_b["parent_id"] is None
    assert inner_b["parent_id"] == outer_b["span_id"]
    assert inner_e["span_id"] == inner_b["span_id"]
    assert outer_e["span_id"] == outer_b["span_id"]


def test_plain_record_carries_the_active_span(tmp_path):
    path = _sink(tmp_path)
    with tracing.span("a", "outer"):
        events.record("worker", "ckpt_saved", iteration=7)
    recs = events.read_events(path)
    assert recs[1]["kind"] == "ckpt_saved"
    assert recs[1]["span_id"] == recs[0]["span_id"]
    # Outside any span (and with no env parent) events carry no span_id.
    events.record("worker", "bare")
    assert "span_id" not in events.read_events(path)[-1]


def test_span_failure_records_error_and_reraises(tmp_path):
    path = _sink(tmp_path)
    with pytest.raises(ValueError):
        with tracing.span("a", "boom"):
            raise ValueError("nope")
    end = events.read_events(path)[-1]
    assert end["kind"] == "span_end" and end["ok"] is False
    assert "ValueError" in end["error"]
    # The failed span was popped: no stale parent leaks onto later events.
    events.record("a", "after")
    assert "span_id" not in events.read_events(path)[-1]


def test_ensure_trace_id_mints_once_and_exports():
    tid = tracing.ensure_trace_id()
    assert os.environ[tracing.TRACE_ID_ENV] == tid
    assert tracing.ensure_trace_id() == tid  # idempotent


def test_traced_decorator(tmp_path):
    path = _sink(tmp_path)

    @tracing.traced("a", "work")
    def f(x):
        return x + 1

    assert f(1) == 2
    kinds = [r["kind"] for r in events.read_events(path)]
    assert kinds == ["span_begin", "span_end"]


def test_child_env_carries_trace_and_active_span():
    tracing.ensure_trace_id()
    with tracing.span("a", "round") as sid:
        env = tracing.child_env()
        assert env[tracing.TRACE_ID_ENV] == tracing.trace_id()
        assert env[tracing.PARENT_SPAN_ENV] == sid
    assert tracing.PARENT_SPAN_ENV not in tracing.child_env()


def test_env_propagation_across_a_spawned_subprocess(tmp_path):
    """The launcher pattern end to end: a child process spawned with
    ``child_env`` parents its spans/events to the spawner's active span and
    shares its trace id — with NO tracing code in the child beyond use."""
    path = str(tmp_path / "x.jsonl")
    os.environ[events.EVENTS_FILE_ENV] = path
    events.clear_sinks()  # child wires itself from the env var
    tid = tracing.ensure_trace_id()
    child = (
        "from tpu_resiliency.utils import events\n"
        "from tpu_resiliency.utils.tracing import span\n"
        "events.record('worker', 'hello')\n"
        "with span('worker', 'work'):\n"
        "    events.record('worker', 'inside')\n"
    )
    with tracing.span("launcher", "launcher.round") as round_sid:
        env = {**os.environ, **tracing.child_env()}
        r = subprocess.run(
            [sys.executable, "-c", child],
            env=env, capture_output=True, text=True, timeout=60,
        )
    assert r.returncode == 0, r.stderr
    recs = events.read_events(path)
    by_kind = {r["kind"]: r for r in recs if r.get("source") == "worker"}
    # Same trace end to end.
    assert all(r["trace_id"] == tid for r in recs if "trace_id" in r)
    # A bare record in the child parents to the spawner's round span...
    assert by_kind["hello"]["span_id"] == round_sid
    # ...the child's own span nests under it...
    worker_begin = next(r for r in recs if r.get("span") == "work"
                        and r["kind"] == "span_begin")
    assert worker_begin["parent_id"] == round_sid
    # ...and records inside the child's span carry the child span's id.
    assert by_kind["inside"]["span_id"] == worker_begin["span_id"]


def test_untraced_process_pays_no_envelope_bytes(tmp_path):
    path = _sink(tmp_path)
    events.record("a", "plain")
    line = open(path).read()
    assert "trace_id" not in line and "span_id" not in line
    rec = json.loads(line)
    assert rec["kind"] == "plain"
