"""utils/timeseries.py: rings, the store, and the pure window helpers."""

import pytest

from tpu_resiliency.utils.timeseries import (
    SeriesRing,
    SeriesStore,
    ewma,
    mad,
    mean_over_time,
    quantile_over_time,
    rate,
    robust_zscore,
)


class TestSeriesRing:
    def test_append_order_and_len(self):
        r = SeriesRing(capacity=8)
        for i in range(5):
            r.observe(float(i), float(i * 10))
        assert len(r) == 5
        assert r.samples() == [(float(i), float(i * 10)) for i in range(5)]
        assert r.last() == (4.0, 40.0)

    def test_overwrites_oldest_when_full(self):
        r = SeriesRing(capacity=4)
        for i in range(10):
            r.observe(float(i), float(i))
        assert len(r) == 4
        assert r.samples() == [(float(i), float(i)) for i in (6, 7, 8, 9)]

    def test_window_is_half_open(self):
        # start < ts <= end: a sample sits in exactly one adjacent window.
        r = SeriesRing(capacity=8)
        for i in range(6):
            r.observe(float(i), float(i))
        lo = r.samples(start=0.0, end=3.0)
        hi = r.samples(start=3.0, end=6.0)
        assert [s[0] for s in lo] == [1.0, 2.0, 3.0]
        assert [s[0] for s in hi] == [4.0, 5.0]

    def test_capacity_validated(self):
        with pytest.raises(ValueError):
            SeriesRing(capacity=0)

    def test_empty(self):
        r = SeriesRing(capacity=4)
        assert len(r) == 0 and r.samples() == [] and r.last() is None


class TestSeriesStore:
    def test_labels_key_series_independently(self):
        st = SeriesStore(capacity=8)
        st.observe("m", 1.0, 10.0, rank="0")
        st.observe("m", 2.0, 20.0, rank="1")
        assert st.query("m", rank="0") == [(1.0, 10.0)]
        assert st.query("m", rank="1") == [(2.0, 20.0)]
        assert st.query("m") == []  # unlabeled series never fed

    def test_label_order_is_canonical(self):
        st = SeriesStore()
        st.observe("m", 1.0, 1.0, a="1", b="2")
        assert st.query("m", b="2", a="1") == [(1.0, 1.0)]

    def test_never_fed_family_queries_empty(self):
        assert SeriesStore().query("nope") == []

    def test_sizes_census(self):
        st = SeriesStore(capacity=4)
        st.observe("m", 1.0, 1.0)
        st.observe("n", 1.0, 1.0, rank="3")
        st.observe("n", 2.0, 2.0, rank="3")
        assert st.sizes() == {"m": 1, "n{rank=3}": 2}


class TestHelpers:
    def test_rate_counter_semantics(self):
        s = [(0.0, 0.0), (5.0, 50.0), (10.0, 100.0)]
        assert rate(s) == pytest.approx(10.0)

    def test_rate_handles_reset(self):
        # A value drop is a restarted emitter: post-reset value counts whole.
        s = [(0.0, 80.0), (5.0, 100.0), (10.0, 30.0)]
        assert rate(s) == pytest.approx((20.0 + 30.0) / 10.0)

    def test_rate_degenerate(self):
        assert rate([]) is None
        assert rate([(1.0, 1.0)]) is None
        assert rate([(1.0, 1.0), (1.0, 2.0)]) is None

    def test_quantile_interpolates(self):
        s = [(float(i), float(v)) for i, v in enumerate([1, 2, 3, 4])]
        assert quantile_over_time(s, 0.5) == pytest.approx(2.5)
        assert quantile_over_time(s, 0.0) == 1.0
        assert quantile_over_time(s, 1.0) == 4.0
        assert quantile_over_time([], 0.5) is None
        assert quantile_over_time([(0.0, 7.0)], 0.99) == 7.0

    def test_mean_and_ewma(self):
        s = [(0.0, 1.0), (1.0, 3.0)]
        assert mean_over_time(s) == 2.0
        assert mean_over_time([]) is None
        assert ewma(s, alpha=0.5) == pytest.approx(2.0)
        assert ewma([]) is None

    def test_mad(self):
        s = [(float(i), v) for i, v in enumerate([1.0, 1.0, 1.0, 10.0])]
        assert mad(s) == pytest.approx(0.0)
        s2 = [(float(i), v) for i, v in enumerate([1.0, 2.0, 3.0])]
        assert mad(s2) == pytest.approx(1.0)

    def test_robust_zscore(self):
        base = [(float(i), v) for i, v in enumerate([1.0, 2.0, 3.0, 2.0, 1.0])]
        z = robust_zscore(10.0, base)
        assert z == pytest.approx((10.0 - 2.0) / (1.4826 * 1.0))

    def test_robust_zscore_steady_baseline_floors_scale(self):
        # A perfectly steady history (MAD 0) is exactly the baseline a
        # straggler spike must register against: scale floors at 1% of the
        # median instead of returning None.
        base = [(float(i), 0.1) for i in range(10)]
        z = robust_zscore(3.0, base)
        assert z is not None and z > 100.0

    def test_robust_zscore_no_scale_at_all(self):
        assert robust_zscore(1.0, [(0.0, 0.0), (1.0, 0.0)]) is None
        assert robust_zscore(1.0, [(0.0, 1.0)]) is None
