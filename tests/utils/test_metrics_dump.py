"""metrics_dump CLI: JSONL → report / Prometheus / JSON."""

import json
import os

import pytest

from tpu_resiliency.tools import metrics_dump
from tpu_resiliency.utils import events, tracing


@pytest.fixture(autouse=True)
def clean():
    events.clear_sinks()
    saved = {
        k: os.environ.pop(k, None)
        for k in (events.EVENTS_FILE_ENV, tracing.TRACE_ID_ENV, tracing.PARENT_SPAN_ENV)
    }
    yield
    events.clear_sinks()
    for k, v in saved.items():
        if v is not None:
            os.environ[k] = v


@pytest.fixture
def run_jsonl(tmp_path):
    """A plausible one-fault run, emitted through the real event layer."""
    path = str(tmp_path / "run.jsonl")
    events.add_sink(events.JsonlSink(path))
    for rnd in (0, 1):
        with tracing.span("rendezvous", "rendezvous.round"):
            pass
        events.record("launcher", "rendezvous_round", round=rnd, world_size=2)
    events.record("launcher", "worker_failed", global_rank=0, exitcode=3)
    events.record("launcher", "restart_requested", reason="rank 0 exit 3")
    for d in (0.02, 0.03):
        events.record("checkpoint", "timing", name="ckpt.save.write",
                      duration_s=d, ok=True, bytes=2048)
    events.record("checkpoint", "ckpt_saved", iteration=1, bytes=2048)
    return path


def test_report_answers_the_operator_questions(run_jsonl, capsys):
    assert metrics_dump.main([run_jsonl]) == 0
    out = capsys.readouterr().out
    assert "in-job requested: 1" in out
    assert "worker failures: 1" in out
    assert "rendezvous rounds: 2" in out
    assert "checkpoint saves: 1" in out
    # The two headline latencies, by name, with quantiles.
    assert "rendezvous round duration: n=2 p50=" in out
    assert "p95=" in out
    assert "checkpoint save/load latency" in out
    assert "ckpt.save.write" in out


def test_prom_output_is_exposition_format(run_jsonl, capsys):
    assert metrics_dump.main([run_jsonl, "--format", "prom"]) == 0
    out = capsys.readouterr().out
    assert "# TYPE tpu_events_total counter" in out
    assert 'tpu_restarts_total{layer="injob"} 1' in out
    assert "# TYPE tpu_span_seconds histogram" in out
    assert 'tpu_span_seconds_count{span="rendezvous.round"} 2' in out


def test_json_output_and_file_write(run_jsonl, tmp_path, capsys):
    assert metrics_dump.main([run_jsonl, "--format", "json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["metrics"]["tpu_worker_failures_total"][0]["value"] == 1
    out = str(tmp_path / "m.json")
    assert metrics_dump.main([run_jsonl, "--format", "json", "-o", out]) == 0
    doc2 = json.load(open(out))
    assert doc2["metrics"].keys() == doc["metrics"].keys()


def test_report_file_write(run_jsonl, tmp_path):
    out = str(tmp_path / "report.txt")
    assert metrics_dump.main([run_jsonl, "-o", out]) == 0
    assert "in-job requested: 1" in open(out).read()


def test_job_slices_a_shared_events_stream(tmp_path, capsys):
    """--job on an events JSONL: only records stamped with that fleet job
    identity aggregate (launcher --fleet-dir exports $TPU_RESILIENCY_JOB)."""
    path = str(tmp_path / "shared.jsonl")
    with open(path, "w") as f:
        for job, n in (("a", 2), ("b", 5)):
            for _ in range(n):
                f.write(json.dumps({
                    "ts": 1.0, "source": "launcher", "kind": "worker_failed",
                    "pid": 1, "rank": None, "job": job,
                }) + "\n")
        f.write(json.dumps({  # unstamped record: in no job's slice
            "ts": 1.0, "source": "launcher", "kind": "worker_failed",
            "pid": 1, "rank": None,
        }) + "\n")
    assert metrics_dump.main([path, "--job", "a", "--format", "prom"]) == 0
    assert "tpu_worker_failures_total 2" in capsys.readouterr().out
    assert metrics_dump.main([path, "--job", "b", "--format", "prom"]) == 0
    assert "tpu_worker_failures_total 5" in capsys.readouterr().out
    assert metrics_dump.main([path, "--job", "nope"]) == 1
    assert "no events for job" in capsys.readouterr().err


def test_job_slices_a_fleet_merged_snapshot(tmp_path, capsys):
    """--job on a metrics snapshot document: keeps one job's series (label
    dropped — the slice IS that job's view), drops fleet:* totals."""
    from tpu_resiliency.utils.metrics import MetricsRegistry

    a, b = MetricsRegistry(), MetricsRegistry()
    a.counter("tpu_restarts_total", "restarts", layer="injob").inc(2)
    b.counter("tpu_restarts_total", "restarts", layer="injob").inc(7)
    fleet = MetricsRegistry()
    fleet.merge(a.snapshot(), extra_labels={"job": "a"})
    fleet.merge(b.snapshot(), extra_labels={"job": "b"})
    fleet.merge({"ts": 0, "metrics": {
        "fleet:tpu_restarts_total": [
            {"type": "counter", "labels": {"layer": "injob"}, "value": 9},
        ],
    }})
    snap = tmp_path / "fleet_metrics.json"
    snap.write_text(json.dumps(fleet.snapshot()))
    assert metrics_dump.main([str(snap), "--job", "a", "--format", "prom"]) == 0
    out = capsys.readouterr().out
    assert 'tpu_restarts_total{layer="injob"} 2' in out
    assert "job=" not in out and "fleet:" not in out
    # --goodput needs a stream, not a snapshot: explicit usage error.
    assert metrics_dump.main([str(snap), "--job", "a", "--goodput"]) == 2


def test_fails_visibly_on_missing_or_empty(tmp_path, capsys):
    assert metrics_dump.main([str(tmp_path / "nope.jsonl")]) == 1
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    assert metrics_dump.main([str(empty)]) == 1
    err = capsys.readouterr().err
    assert "cannot read" in err and "no events" in err
