"""The metric registry and its documentation cannot drift: every metric name
the events→metrics bridge can emit must appear in docs/observability.md.

The names are extracted from ``utils/metrics.py`` by AST walk (first
positional string literal of every ``.counter(`` / ``.gauge(`` /
``.histogram(`` call), so adding a metric without documenting it fails CI —
the audit the ISSUE's PR-4/5 metrics slipped past when this test didn't
exist."""

import ast
import os

import tpu_resiliency.utils.metrics as metrics_mod

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
DOC = os.path.join(REPO, "docs", "observability.md")


def registered_metric_names() -> set[str]:
    with open(metrics_mod.__file__) as f:
        tree = ast.parse(f.read())
    names = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not (isinstance(fn, ast.Attribute)
                and fn.attr in ("counter", "gauge", "histogram")):
            continue
        if node.args and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            names.add(node.args[0].value)
    return names


def test_extraction_finds_the_known_core():
    names = registered_metric_names()
    # Sanity floor: the extraction must see the families every PR relied on.
    assert {"tpu_events_total", "tpu_restarts_total", "tpu_ckpt_saves_total",
            "tpu_incidents_total", "tpu_remediation_actions_total"} <= names
    assert len(names) >= 30


def test_every_registered_metric_is_documented():
    names = registered_metric_names()
    with open(DOC) as f:
        doc = f.read()
    missing = sorted(n for n in names if n not in doc)
    assert not missing, (
        f"metrics registered in utils/metrics.py but absent from "
        f"docs/observability.md: {missing} — document them in the registry "
        f"section (this test is the drift gate)"
    )


def test_incident_slo_metrics_are_registered_and_documented():
    """The PR-6 SLO surface specifically: both ends of the contract."""
    names = registered_metric_names()
    with open(DOC) as f:
        doc = f.read()
    for metric in (
        "tpu_incidents_total",
        "tpu_incidents_open",
        "tpu_incident_time_to_detect_seconds",
        "tpu_incident_time_to_decide_seconds",
        "tpu_incident_time_to_recover_seconds",
        "tpu_incident_steps_lost_total",
        "tpu_remediation_actions_total",
        "tpu_flight_flushes_total",
    ):
        assert metric in names, f"{metric} not registered"
        assert metric in doc, f"{metric} not documented"
