"""Critical-path analyzer: span collection, milestone decomposition (the
arithmetic bench_restart.py publishes), the dominant chain, self-time, and
the tpu-critpath CLI with highlighted trace export."""

import json

import pytest

from tpu_resiliency.tools import critpath, trace_export

T = 1000.0


def _restart_stream():
    return [
        {"ts": T + 0.000, "kind": "worker_failed", "source": "launcher", "pid": 1},
        {"ts": T + 0.004, "kind": "failure_detected", "source": "launcher", "pid": 1},
        {"ts": T + 0.004, "kind": "span_begin", "span": "launcher.round",
         "source": "launcher", "pid": 1, "span_id": "aaa"},
        {"ts": T + 0.020, "kind": "restart_requested", "source": "launcher", "pid": 1},
        {"ts": T + 0.021, "kind": "span_begin", "span": "rendezvous.round",
         "source": "rendezvous", "pid": 1, "span_id": "bbb", "parent_id": "aaa"},
        {"ts": T + 0.050, "kind": "span_end", "span": "rendezvous.round",
         "source": "rendezvous", "pid": 1, "span_id": "bbb", "duration_s": 0.029},
        {"ts": T + 0.050, "kind": "rendezvous_round", "source": "launcher",
         "pid": 1, "round": 1},
        {"ts": T + 0.060, "kind": "worker_promoted", "source": "launcher",
         "pid": 1, "outcome": "promoted", "round": 1},
        {"ts": T + 0.061, "kind": "rendezvous_fast_path", "outcome": "reused",
         "source": "rendezvous", "pid": 1},
        {"ts": T + 0.090, "kind": "iteration_start", "source": "inprocess",
         "pid": 2, "iteration": 5},
        {"ts": T + 0.100, "kind": "span_end", "span": "launcher.round",
         "source": "launcher", "pid": 1, "span_id": "aaa", "duration_s": 0.096},
    ]


def test_collect_spans_pairs_and_flags_unfinished():
    recs = _restart_stream() + [
        {"ts": T + 0.05, "kind": "span_begin", "span": "worker.spawn",
         "source": "launcher", "pid": 3, "span_id": "ccc"},
    ]
    spans = critpath.collect_spans(recs)
    by_name = {s.name: s for s in spans}
    assert by_name["rendezvous.round"].finished
    assert by_name["rendezvous.round"].parent_id == "aaa"
    assert not by_name["worker.spawn"].finished
    assert by_name["worker.spawn"].t1 == pytest.approx(T + 0.100)


def test_restart_decomposition_matches_published_arithmetic():
    dec = critpath.restart_decomposition(_restart_stream())
    segs = {s["name"]: s["duration_ms"] for s in dec["segments"]}
    assert segs["detect"] == pytest.approx(4.0, abs=0.01)
    assert segs["teardown"] == pytest.approx(16.0, abs=0.01)
    assert segs["rendezvous"] == pytest.approx(30.0, abs=0.01)
    assert segs["promote"] == pytest.approx(10.0, abs=0.01)
    assert segs["first_step_ready"] == pytest.approx(30.0, abs=0.01)
    assert dec["fast_path"] and dec["promoted"]
    assert dec["total_ms"] == pytest.approx(90.0, abs=0.01)


def test_restart_decomposition_external_anchors():
    """The benchmark's stamp-file anchors override the stream's own fault/
    resume evidence — the published numbers and the pure-events view share
    one arithmetic with different endpoints."""
    dec = critpath.restart_decomposition(
        _restart_stream(), fault_ts=T - 0.002, resume_ts=T + 0.080
    )
    segs = {s["name"]: s["duration_ms"] for s in dec["segments"]}
    assert segs["detect"] == pytest.approx(6.0, abs=0.01)
    assert segs["first_step_ready"] == pytest.approx(20.0, abs=0.01)


def test_inverted_milestones_clamp_to_zero():
    dec = critpath.restart_decomposition(
        _restart_stream(), resume_ts=T + 0.059  # beats the promote stamp
    )
    segs = {s["name"]: s["duration_ms"] for s in dec["segments"]}
    assert segs["first_step_ready"] == 0.0


def test_cold_restart_reports_spawn_segment():
    recs = [r for r in _restart_stream() if r["kind"] != "worker_promoted"]
    dec = critpath.restart_decomposition(recs)
    segs = {s["name"] for s in dec["segments"]}
    assert "spawn_and_startup" in segs and "promote" not in segs
    assert not dec["promoted"]


def test_dominant_chain_descends_into_children_and_covers_window():
    doc = critpath.analyze(_restart_stream())
    ep = doc["episodes"][0]
    chain = ep["chain"]
    assert any(seg["span"] == "rendezvous.round" for seg in chain)
    # Contiguous cover of [t_fault, t_end], gaps explicit.
    assert chain[0]["start"] == pytest.approx(ep["t_fault"])
    for a, b in zip(chain, chain[1:]):
        assert a["end"] == pytest.approx(b["start"])
    assert chain[-1]["end"] == pytest.approx(ep["t_end"])
    assert chain[0]["span"] == "(gap)"  # nothing instrumented covers detect


def test_self_time_subtracts_children():
    spans = critpath.collect_spans(_restart_stream())
    parent = next(s for s in spans if s.name == "launcher.round")
    # 96 ms span minus the 29 ms rendezvous child.
    assert critpath.self_time(parent, spans) == pytest.approx(0.067, abs=1e-6)


def test_multiple_episodes_found():
    second = []
    for r in _restart_stream():
        r2 = dict(r)
        r2["ts"] = r["ts"] + 10.0
        for k in ("span_id", "parent_id"):
            if k in r2:
                r2[k] = r2[k] + "2"
        second.append(r2)
    eps = critpath.find_restart_episodes(_restart_stream() + second)
    assert len(eps) == 2
    assert eps[1]["t_fault"] == pytest.approx(T + 10.0)


def test_window_fallback_without_restart():
    recs = [
        {"ts": T, "kind": "span_begin", "span": "ckpt.save.enqueue",
         "source": "checkpoint", "pid": 1, "span_id": "s1"},
        {"ts": T + 0.5, "kind": "span_end", "span": "ckpt.save.enqueue",
         "source": "checkpoint", "pid": 1, "span_id": "s1", "duration_s": 0.5},
    ]
    doc = critpath.analyze(recs)
    assert doc["episodes"][0]["kind"] == "window"
    assert any(s["span"] == "ckpt.save.enqueue"
               for s in doc["episodes"][0]["chain"])


def test_reshard_decomposition():
    recs = [
        {"ts": T, "kind": "span_begin", "span": "reshard.plan",
         "source": "checkpoint", "pid": 1, "span_id": "p1"},
        {"ts": T + 0.01, "kind": "span_end", "span": "reshard.plan",
         "source": "checkpoint", "pid": 1, "span_id": "p1", "duration_s": 0.01},
        {"ts": T + 0.02, "kind": "span_begin", "span": "reshard.fetch",
         "source": "checkpoint", "pid": 1, "span_id": "f1"},
        {"ts": T + 0.10, "kind": "span_end", "span": "reshard.fetch",
         "source": "checkpoint", "pid": 1, "span_id": "f1", "duration_s": 0.08},
        {"ts": T + 0.10, "kind": "reshard_fetch", "via": "peer", "holder": 2,
         "bytes": 1024, "pid": 1},
        {"ts": T + 0.11, "kind": "reshard_fetch", "via": "local",
         "bytes": 2048, "pid": 1},
    ]
    d = critpath.reshard_decomposition(recs)
    assert d["plan_s"] == pytest.approx(0.01)
    assert d["fetch_s"] == pytest.approx(0.08)
    assert d["peer_bytes"] == 1024 and d["local_bytes"] == 2048
    assert d["peer_fetches"] == 1


def test_critical_span_ids_feed_trace_highlight():
    doc = critpath.analyze(_restart_stream())
    ids = critpath.critical_span_ids(doc)
    assert "bbb" in ids
    trace = trace_export.to_chrome_trace(_restart_stream(), critical_ids=ids)
    crit = [e for e in trace["traceEvents"]
            if e.get("args", {}).get("critical_path")]
    assert any(e["name"] == "rendezvous.round" for e in crit)
    assert all(e.get("cname") for e in crit)


# -- CLI ----------------------------------------------------------------------


def _write(tmp_path, recs):
    path = tmp_path / "ev.jsonl"
    with open(path, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    return str(path)


def test_cli_table_names_segments_and_chain(tmp_path, capsys):
    path = _write(tmp_path, _restart_stream())
    assert critpath.main([path]) == 0
    out = capsys.readouterr().out
    for want in ("restart episode", "detect", "rendezvous", "promote",
                 "rendezvous.round", "fast-path rendezvous"):
        assert want in out, out


def test_cli_json_document(tmp_path, capsys):
    path = _write(tmp_path, _restart_stream())
    assert critpath.main([path, "--format", "json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["schema"] == "tpu-critpath-1"
    assert doc["episodes"][0]["kind"] == "restart"


def test_cli_trace_export_highlights(tmp_path, capsys):
    path = _write(tmp_path, _restart_stream())
    trace_path = tmp_path / "crit.trace.json"
    assert critpath.main([path, "--trace", str(trace_path)]) == 0
    doc = json.loads(trace_path.read_text())
    assert any(e.get("args", {}).get("critical_path")
               for e in doc["traceEvents"])


def test_cli_restart_mode_exits_1_without_episode(tmp_path, capsys):
    path = _write(tmp_path, [
        {"ts": T, "kind": "iteration_start", "pid": 1, "iteration": 0,
         "source": "inprocess"},
    ])
    assert critpath.main([path, "--episode", "restart"]) == 1


def test_cli_missing_file():
    assert critpath.main(["/nonexistent/ev.jsonl"]) == 1
