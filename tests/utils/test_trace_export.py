"""Chrome trace export: span pairing → X slices, instants, round trip."""

import json
import os

import pytest

from tpu_resiliency.tools import trace_export
from tpu_resiliency.utils import events, tracing


@pytest.fixture(autouse=True)
def clean():
    events.clear_sinks()
    saved = {
        k: os.environ.pop(k, None)
        for k in (events.EVENTS_FILE_ENV, tracing.TRACE_ID_ENV, tracing.PARENT_SPAN_ENV)
    }
    yield
    events.clear_sinks()
    for k, v in saved.items():
        if v is not None:
            os.environ[k] = v


def _synthetic_stream(tmp_path):
    """A real stream from the real emitters: spans + plain events."""
    path = str(tmp_path / "ev.jsonl")
    events.add_sink(events.JsonlSink(path))
    tracing.ensure_trace_id()
    with tracing.span("launcher", "launcher.round", round=0):
        events.record("launcher", "worker_failed", global_rank=1, exitcode=3)
        with tracing.span("rendezvous", "rendezvous.round"):
            pass
    return path


def test_matched_span_becomes_complete_slice(tmp_path):
    path = _synthetic_stream(tmp_path)
    trace = trace_export.to_chrome_trace(events.read_events(path))
    slices = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    names = {e["name"] for e in slices}
    assert names == {"launcher.round", "rendezvous.round"}
    round_slice = next(e for e in slices if e["name"] == "launcher.round")
    assert round_slice["dur"] >= 0 and round_slice["args"]["round"] == 0
    assert "span_id" in round_slice["args"]
    # Instants survive with their payload.
    instants = [e for e in trace["traceEvents"] if e["ph"] == "i"]
    assert any(e["name"] == "worker_failed" and e["args"]["exitcode"] == 3
               for e in instants)
    # Process metadata rows name the pid.
    metas = [e for e in trace["traceEvents"] if e["ph"] == "M"]
    assert metas and all(e["name"] == "process_name" for e in metas)


def test_unmatched_begin_renders_unfinished_to_stream_end(tmp_path):
    """A process that dies inside a span (the case worth debugging) still
    shows the span, flagged and extended to the last event."""
    recs = [
        {"ts": 10.0, "source": "w", "kind": "span_begin", "pid": 5,
         "span_id": "aa", "span": "doomed"},
        {"ts": 12.0, "source": "w", "kind": "worker_failed", "pid": 5},
    ]
    trace = trace_export.to_chrome_trace(recs)
    x = next(e for e in trace["traceEvents"] if e["ph"] == "X")
    assert x["name"] == "doomed" and x["args"]["unfinished"] is True
    assert x["dur"] == pytest.approx(2e6)  # microseconds to end of stream


def test_multiple_unfinished_spans_across_pids_all_survive():
    """A multi-rank crash (every worker dies mid-span) must render EVERY open
    span — none silently dropped — each extended to the trace's last
    timestamp and visually flagged (distinct cname + unfinished arg)."""
    recs = [
        {"ts": 10.0, "source": "w", "kind": "span_begin", "pid": 5,
         "span_id": "aa", "span": "step", "rank": 0},
        {"ts": 11.0, "source": "w", "kind": "span_begin", "pid": 6,
         "span_id": "bb", "span": "step", "rank": 1},
        {"ts": 12.0, "source": "w", "kind": "span_begin", "pid": 6,
         "span_id": "cc", "span": "barrier", "rank": 1},  # nested, also open
        {"ts": 14.0, "source": "launcher", "kind": "worker_failed", "pid": 1},
    ]
    trace = trace_export.to_chrome_trace(recs)
    slices = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert len(slices) == 3  # all three open spans survive
    assert all(e["args"]["unfinished"] is True for e in slices)
    assert all(e["cname"] == "terrible" for e in slices)
    # Each extends exactly to the last event of the whole trace.
    by_span = {e["args"]["span_id"]: e for e in slices}
    assert by_span["aa"]["dur"] == pytest.approx(4e6)
    assert by_span["bb"]["dur"] == pytest.approx(3e6)
    assert by_span["cc"]["dur"] == pytest.approx(2e6)
    # Rows stay per-rank: the two pids don't collapse onto one track.
    assert {e["pid"] for e in slices} == {5, 6}


def test_finished_spans_carry_no_crash_color(tmp_path):
    path = _synthetic_stream(tmp_path)
    trace = trace_export.to_chrome_trace(events.read_events(path))
    slices = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert slices and all("cname" not in e for e in slices)
    assert all("unfinished" not in e["args"] for e in slices)


def test_cli_reports_unfinished_count(tmp_path, capsys):
    import json as _json

    path = tmp_path / "ev.jsonl"
    recs = [
        {"ts": 1.0, "source": "w", "kind": "span_begin", "pid": 5,
         "span_id": "aa", "span": "doomed"},
        {"ts": 2.0, "source": "w", "kind": "worker_failed", "pid": 5},
    ]
    path.write_text("".join(_json.dumps(r) + "\n" for r in recs))
    out = tmp_path / "t.json"
    assert trace_export.main([str(path), "-o", str(out)]) == 0
    assert "1 UNFINISHED" in capsys.readouterr().out


def test_orphan_end_degrades_to_instant():
    recs = [
        {"ts": 1.0, "source": "w", "kind": "span_end", "pid": 5,
         "span_id": "zz", "span": "headless", "duration_s": 0.5, "ok": True},
    ]
    trace = trace_export.to_chrome_trace(recs)
    assert [e["ph"] for e in trace["traceEvents"] if e["ph"] != "M"] == ["i"]


def test_garbage_and_empty_streams():
    assert trace_export.to_chrome_trace([]) == {
        "traceEvents": [], "displayTimeUnit": "ms"
    }
    # ts-less / kind-less records are dropped, not crashed on.
    assert trace_export.to_chrome_trace(
        [{"kind": "x"}, {"ts": 1.0}, {"ts": "bad", "kind": "y"}]
    )["traceEvents"] == []


def test_cli_round_trip_produces_loadable_json(tmp_path, capsys):
    path = _synthetic_stream(tmp_path)
    out = str(tmp_path / "trace.json")
    assert trace_export.main([path, "-o", out]) == 0
    assert "perfetto" in capsys.readouterr().out
    doc = json.load(open(out))  # Perfetto-loadable == valid trace-event JSON
    assert isinstance(doc["traceEvents"], list) and doc["traceEvents"]
    phases = {e["ph"] for e in doc["traceEvents"]}
    assert phases <= {"X", "i", "M"}
    # Every slice/instant has the required fields.
    for e in doc["traceEvents"]:
        assert {"name", "ph", "pid"} <= set(e)
        if e["ph"] != "M":
            assert "ts" in e and "tid" in e


def test_cli_fails_visibly(tmp_path, capsys):
    assert trace_export.main([str(tmp_path / "missing.jsonl")]) == 1
    assert "cannot read" in capsys.readouterr().err
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    assert trace_export.main([str(empty)]) == 1


def test_slices_carry_self_time_and_critical_highlight(tmp_path):
    recs = [
        {"ts": 1.0, "source": "l", "kind": "span_begin", "pid": 5,
         "span_id": "par", "span": "launcher.round"},
        {"ts": 1.2, "source": "r", "kind": "span_begin", "pid": 5,
         "span_id": "kid", "span": "rendezvous.round", "parent_id": "par"},
        {"ts": 1.5, "source": "r", "kind": "span_end", "pid": 5,
         "span_id": "kid", "span": "rendezvous.round", "duration_s": 0.3},
        {"ts": 2.0, "source": "l", "kind": "span_end", "pid": 5,
         "span_id": "par", "span": "launcher.round", "duration_s": 1.0},
    ]
    trace = trace_export.to_chrome_trace(recs, critical_ids={"kid"})
    slices = {e["args"]["span_id"]: e for e in trace["traceEvents"]
              if e["ph"] == "X"}
    # Parent self-time excludes the child's 300 ms window.
    assert slices["par"]["args"]["self_time_ms"] == pytest.approx(700.0)
    assert slices["kid"]["args"]["self_time_ms"] == pytest.approx(300.0)
    # The critical-path span is highlighted; the parent is not.
    assert slices["kid"]["args"].get("critical_path") is True
    assert slices["kid"].get("cname")
    assert "critical_path" not in slices["par"]["args"]
    assert slices["par"].get("cname") is None


def test_unfinished_accounting_survives_highlighting():
    recs = [
        {"ts": 1.0, "source": "w", "kind": "span_begin", "pid": 5,
         "span_id": "open", "span": "worker.spawn"},
        {"ts": 2.0, "source": "w", "kind": "iteration_start", "pid": 5,
         "iteration": 0},
    ]
    trace = trace_export.to_chrome_trace(recs, critical_ids={"open"})
    sl = next(e for e in trace["traceEvents"] if e["ph"] == "X")
    assert sl["args"]["unfinished"] is True
    assert sl["cname"] == "terrible"  # unfinished red wins over the highlight
    assert sl["args"]["critical_path"] is True
