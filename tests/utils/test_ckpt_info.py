"""ckpt_info CLI: offline coverage audit over a real manager-written root."""

import io
import os

import numpy as np

from tpu_resiliency.checkpoint.local_manager import LocalCheckpointManager
from tpu_resiliency.checkpoint.state_dict import PyTreeStateDict
from tpu_resiliency.tools import ckpt_info


def _save(mgr, iteration, value):
    mgr.save(
        iteration,
        PyTreeStateDict({"w": np.full((4,), value, np.float32)}),
        is_async=False,
    )


def test_scan_and_render_real_root(tmp_path):
    root = str(tmp_path)
    m0 = LocalCheckpointManager(root, rank=0)
    m1 = LocalCheckpointManager(root, rank=1)
    for it in (1, 2):
        _save(m0, it, 0.0)
        _save(m1, it, 1.0)
    m0.close()
    m1.close()

    info = ckpt_info.scan(root)[0]
    assert info.ranks == {0, 1} and info.owners == {0, 1}
    # Retention keeps only the newest iteration per rank (manager semantics):
    # both ranks hold iter 2, and the audit agrees it is resumable.
    assert info.covered_iterations() == [2]
    out = io.StringIO()
    ckpt_info.render(info, out=out)
    text = out.getvalue()
    assert "auditing world=[0, 1] (1 iterations on disk)" in text
    assert "iter       2: owners [0, 1]" in text and "[COVERED]" in text
    assert "resumable from: iter 2 (newest covered for world [0, 1])" in text

    # One rank advances alone (the crashed-mid-save-cycle shape): the audit
    # must show the divergence and that NOTHING is now fully covered.
    m0b = LocalCheckpointManager(root, rank=0)
    _save(m0b, 3, 0.0)
    m0b.close()
    info2 = ckpt_info.scan(root)[0]
    assert info2.covered_iterations() == []
    out2 = io.StringIO()
    ckpt_info.render(info2, out=out2)
    text2 = out2.getvalue()
    assert "iter       2: owners [1]" in text2 and "missing owners [0]" in text2
    assert "iter       3: owners [0]" in text2 and "missing owners [1]" in text2
    assert "resumable from: NOTHING for world [0, 1]" in text2
    # Group-relative coverage: the audit names the shrunk world iter 3 serves.
    assert "covers a (shrunk) world of [0]" in text2 and "--world 0" in text2
    # And auditing AS that shrunk world flips the verdict.
    out3 = io.StringIO()
    ckpt_info.render(info2, out=out3, world={0})
    assert "resumable from: iter 3 (newest covered for world [0])" in out3.getvalue()


def test_mirrors_and_dirty_files(tmp_path):
    root = str(tmp_path)
    m0 = LocalCheckpointManager(root, rank=0)
    _save(m0, 5, 0.0)
    m0.close()
    # Simulate a replicated mirror: rank 1 holds a copy of rank 0's shard.
    r1 = os.path.join(root, "s0", "r1")
    os.makedirs(r1)
    src = os.path.join(root, "s0", "r0", "iter_0000005_0_local.ckpt")
    with open(src, "rb") as f, open(os.path.join(r1, "iter_0000005_0_local.ckpt"), "wb") as g:
        g.write(f.read())
    # And a torn temp from a crashed save.
    with open(os.path.join(r1, "iter_0000006_1_local.ckpt.dirty"), "w") as f:
        f.write("torn")

    info = ckpt_info.scan(root)[0]
    # World is {0, 1} (rank dir r1 exists) but only owner 0 ever saved: with
    # owner 1's shard absent everywhere, nothing is covered for a 2-rank world.
    assert info.ranks == {0, 1}
    assert info.covered_iterations() == []
    out = io.StringIO()
    ckpt_info.render(info, out=out)
    text = out.getvalue()
    assert "1 mirror copies" in text
    assert "resumable from: NOTHING" in text
    assert "torn save temp" in text and "iter_0000006_1_local.ckpt.dirty" in text


def test_cli_main(tmp_path, capsys):
    m = LocalCheckpointManager(str(tmp_path), rank=0)
    _save(m, 7, 2.5)
    m.close()
    assert ckpt_info.main([str(tmp_path)]) == 0
    assert "resumable from: iter 7" in capsys.readouterr().out  # single-rank world
    assert ckpt_info.main([str(tmp_path / "nope")]) == 1


def test_scan_survives_session_dir_unlinked_mid_audit(tmp_path, monkeypatch):
    """A retention prune (or operator rm) deleting a session directory between
    the root listing and the per-session listing must skip that session, not
    abort the whole audit."""
    import shutil

    root = tmp_path / "root"
    for s in ("s0", "s1"):
        d = root / s / "r0"
        d.mkdir(parents=True)
        (d / "iter_0000005_0_local.ckpt").write_bytes(b"x" * 10)

    doomed = str(root / "s0")
    real_listdir = os.listdir

    def racing_listdir(p):
        # Unlink s0 the moment the scanner descends into it.
        if str(p) == doomed and os.path.isdir(doomed):
            shutil.rmtree(doomed)
        return real_listdir(p)

    monkeypatch.setattr(os, "listdir", racing_listdir)
    sessions = ckpt_info.scan(str(root))
    assert [s.session for s in sessions] == [1]  # s0 skipped, audit completed


def test_scan_survives_root_unlinked(tmp_path):
    assert ckpt_info.scan(str(tmp_path / "gone")) == []


def _save_layout_root(tmp_path):
    """A 2-rank layout-bearing root written by real managers (no comm: each
    rank's own shard only — plus hand-mirrored copies for the plan split)."""
    from tpu_resiliency.checkpoint import reshard as R

    root = str(tmp_path)
    G = np.arange(12 * 2, dtype=np.float32).reshape(12, 2)
    layout = R.TreeLayout(
        [("dp", 2)], [0, 1], [R.LeafSpec((12, 2), "float32", ("dp",))]
    )
    for rank in (0, 1):
        mgr = LocalCheckpointManager(root, rank=rank)
        mgr.save(
            1,
            PyTreeStateDict({"w": R.slice_local([G], layout, rank)[0]}),
            is_async=False,
            layout=layout,
        )
        mgr.close()
    return root


def test_plan_renders_split_and_exits_zero(tmp_path):
    root = _save_layout_root(tmp_path)
    out = io.StringIO()
    rc = ckpt_info.render_plan(ckpt_info.scan(root)[0], {0}, out=out)
    text = out.getvalue()
    assert rc == 0, text
    assert "reshard plan 2 -> 1 ranks (shrink)" in text
    assert "via local" in text and "via peer-fetch" in text
    assert "coverage: OK for world [0]" in text


def test_plan_uncovered_exits_one_naming_ranks(tmp_path):
    import shutil

    root = _save_layout_root(tmp_path)
    shutil.rmtree(os.path.join(root, "s0", "r1"))
    out = io.StringIO()
    rc = ckpt_info.render_plan(ckpt_info.scan(root)[0], {0}, out=out)
    text = out.getvalue()
    assert rc == 1, text
    assert "UNCOVERED: no surviving copy of source rank(s) [1]" in text


def test_plan_cli_main(tmp_path, capsys):
    root = _save_layout_root(tmp_path)
    assert ckpt_info.main([root, "--world", "0", "--plan"]) == 0
    assert "reshard plan" in capsys.readouterr().out
    # --plan without --world is a usage error
    assert ckpt_info.main([root, "--plan"]) == 2
    # explicit axes spec parses and plans
    assert (
        ckpt_info.main(
            [root, "--world", "0,1", "--plan", "--axes", "dp=1,tp=2"]
        )
        == 0
    )
    out = capsys.readouterr().out
    assert "target axes {'dp': 1, 'tp': 2}" in out


def test_plan_without_layout_meta_exits_one(tmp_path, capsys):
    root = str(tmp_path)
    mgr = LocalCheckpointManager(root, rank=0)
    _save(mgr, 1, 0.0)
    mgr.close()
    assert ckpt_info.main([root, "--world", "0", "--plan"]) == 1
    assert "no containers carry reshard layout" in capsys.readouterr().out


def _spill_to_cold(root, cold_dir, pairs):
    """Archive ``[(iteration, owner)]`` from a manager-written ``root`` into
    a cold tier at ``cold_dir`` (the spiller's real path, drained)."""
    from tpu_resiliency.checkpoint.coldtier import ColdTier, FilesystemStore

    tier = ColdTier(FilesystemStore(cold_dir), session=0, rank=0)
    try:
        for it, owner in pairs:
            path = os.path.join(
                root, "s0", f"r{owner}", f"iter_{it:07d}_{owner}_local.ckpt"
            )
            assert tier.spill(it, owner, path)
        assert tier.flush(timeout=30.0)
    finally:
        tier.close()
    return tier


def test_cold_coverage_joins_render(tmp_path, capsys):
    """A shard lost after it was archived: local coverage alone is NOTHING,
    but --cold restores the verdict through the third rung."""
    root = str(tmp_path / "root")
    cold = str(tmp_path / "cold")
    for rank in (0, 1):
        mgr = LocalCheckpointManager(root, rank=rank)
        _save(mgr, 2, float(rank))
        mgr.close()
    _spill_to_cold(root, cold, [(2, 0), (2, 1)])
    # Lose rank 1's container but keep its rank dir (disk scrub, not shrink):
    # the audited world stays [0, 1] with owner 1's shard gone locally.
    os.unlink(
        os.path.join(root, "s0", "r1", "iter_0000002_1_local.ckpt")
    )

    # Without --cold: owner 1's shard is gone everywhere.
    assert ckpt_info.main([root]) == 0
    text = capsys.readouterr().out
    assert "resumable from: NOTHING for world [0, 1]" in text

    assert ckpt_info.main([root, "--cold", cold]) == 0
    text = capsys.readouterr().out
    assert "1 in cold tier" in text
    assert "cold: [0, 1]" in text
    assert "[COVERED]" in text
    assert "resumable from: iter 2 (newest covered for world [0, 1])" in text


def test_cold_only_session_audits_from_empty_workdir(tmp_path, capsys):
    """The restore-anywhere audit: a freshly provisioned (empty) workdir plus
    --cold still names what a new job could bootstrap from."""
    root = str(tmp_path / "root")
    cold = str(tmp_path / "cold")
    mgr = LocalCheckpointManager(root, rank=0)
    _save(mgr, 3, 1.5)
    mgr.close()
    _spill_to_cold(root, cold, [(3, 0)])

    empty = str(tmp_path / "fresh")
    os.makedirs(empty)
    assert ckpt_info.main([empty]) == 1  # no sessions without the cold rung
    capsys.readouterr()
    assert ckpt_info.main([empty, "--cold", cold]) == 0
    text = capsys.readouterr().out
    assert "session 0" in text and "cold: [0]" in text
    assert "resumable from: iter 3" in text


def test_cold_verify_catches_archived_corruption(tmp_path, capsys):
    root = str(tmp_path / "root")
    cold = str(tmp_path / "cold")
    mgr = LocalCheckpointManager(root, rank=0)
    _save(mgr, 4, 2.0)
    mgr.close()
    _spill_to_cold(root, cold, [(4, 0)])

    assert ckpt_info.main([root, "--cold", cold, "--verify"]) == 0
    text = capsys.readouterr().out
    assert "verifying 1 cold artifact(s)" in text
    assert "cold s0/iter 4 owner 0" in text and "[OK" in text

    # Flip one payload byte in the archived object: the manifest digest must
    # fail the artifact and the CLI must exit 1.
    akey = os.path.join(cold, "s0", "iter_0000004", "owner_0.ckpt")
    blob = bytearray(open(akey, "rb").read())
    blob[len(blob) // 2] ^= 0x40
    with open(akey, "wb") as f:
        f.write(bytes(blob))
    assert ckpt_info.main([root, "--cold", cold, "--verify"]) == 1
    text = capsys.readouterr().out
    assert "digest mismatch" in text


def test_cold_missing_dir_is_an_error(tmp_path, capsys):
    root = str(tmp_path)
    mgr = LocalCheckpointManager(root, rank=0)
    _save(mgr, 1, 0.0)
    mgr.close()
    assert ckpt_info.main([root, "--cold", str(tmp_path / "nope")]) == 1
    assert "not a cold-tier root" in capsys.readouterr().err
