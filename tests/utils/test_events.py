"""Structured event stream: records, sinks, env wiring, @prof timing."""

import json
import os

import pytest

from tpu_resiliency.utils import events


@pytest.fixture(autouse=True)
def clean_sinks():
    events.clear_sinks()
    old = os.environ.pop(events.EVENTS_FILE_ENV, None)
    yield
    events.clear_sinks()
    if old is not None:
        os.environ[events.EVENTS_FILE_ENV] = old


def test_record_to_jsonl_sink(tmp_path):
    path = str(tmp_path / "ev.jsonl")
    sink = events.JsonlSink(path)
    events.add_sink(sink)
    events.record("launcher", "rendezvous_round", round=3, world_size=8)
    events.record("inprocess", "restart_signalled", iteration=1)
    sink.close()
    recs = events.read_events(path)
    assert [r["kind"] for r in recs] == ["rendezvous_round", "restart_signalled"]
    assert recs[0]["source"] == "launcher" and recs[0]["round"] == 3
    assert recs[0]["pid"] == os.getpid()
    assert "ts" in recs[0]


def test_env_var_wires_sink(tmp_path):
    path = str(tmp_path / "env_ev.jsonl")
    os.environ[events.EVENTS_FILE_ENV] = path
    events.record("watchdog", "hang_detected", global_rank=5, reason="hb timeout")
    recs = events.read_events(path)
    assert len(recs) == 1 and recs[0]["global_rank"] == 5


def test_rank_from_env(tmp_path, monkeypatch):
    path = str(tmp_path / "r.jsonl")
    events.add_sink(events.JsonlSink(path))
    monkeypatch.setenv("RANK", "7")
    events.record("checkpoint", "ckpt_saved", iteration=40)
    assert events.read_events(path)[0]["rank"] == 7


def test_sink_failure_never_raises():
    def bad_sink(ev):
        raise RuntimeError("sink down")

    events.add_sink(bad_sink)
    events.record("launcher", "anything")  # must not raise


def test_reserved_payload_keys_do_not_collide(tmp_path):
    path = str(tmp_path / "c.jsonl")
    events.add_sink(events.JsonlSink(path))
    events.record("x", "y", ts=123, pid=-1)
    rec = events.read_events(path)[0]
    assert rec["source"] == "x" and rec["ts"] != 123  # envelope wins
    assert rec["p_ts"] == 123 and rec["p_pid"] == -1


def test_prof_decorator(tmp_path):
    path = str(tmp_path / "p.jsonl")
    events.add_sink(events.JsonlSink(path))

    @events.prof("checkpoint")
    def work(x):
        return x * 2

    @events.prof("checkpoint", name="explode")
    def bad():
        raise ValueError("nope")

    assert work(21) == 42
    with pytest.raises(ValueError):
        bad()
    recs = events.read_events(path)
    assert recs[0]["kind"] == "timing" and recs[0]["name"] == "work" and recs[0]["ok"]
    assert recs[1]["name"] == "explode" and not recs[1]["ok"]
    assert "ValueError" in recs[1]["error"]
    assert recs[0]["duration_s"] >= 0


def test_read_events_tolerates_torn_line(tmp_path):
    path = tmp_path / "torn.jsonl"
    path.write_text(json.dumps({"kind": "a"}) + "\n" + '{"kind": "b", "tru')
    assert [r["kind"] for r in events.read_events(str(path))] == ["a"]


def test_read_events_window_filters_at_read_time(tmp_path):
    path = tmp_path / "win.jsonl"
    path.write_text("\n".join(json.dumps(r) for r in [
        {"kind": "old", "ts": 10.0},
        {"kind": "in_a", "ts": 20.0},
        {"kind": "no_ts"},
        {"kind": "in_b", "ts": 25.0},
        {"kind": "future", "ts": 99.0},
    ]) + "\n")
    recs = events.read_events(str(path), since=20.0, until=30.0)
    assert [r["kind"] for r in recs] == ["in_a", "in_b"]
    # Unbounded read keeps everything, ts-less records included.
    assert len(events.read_events(str(path))) == 5


def test_debug_time_nesting_and_event(tmp_path, caplog):
    import logging

    from tpu_resiliency.utils import events
    from tpu_resiliency.utils.timers import debug_time

    path = str(tmp_path / "t.jsonl")
    events.add_sink(events.JsonlSink(path))

    with caplog.at_level(logging.DEBUG, logger="tpu_resiliency"):
        with debug_time("outer", source="checkpoint"):
            with debug_time("inner", source="checkpoint"):
                pass

    lines = [r.message for r in caplog.records if "ms" in r.message]
    assert any(m.startswith("  inner:") for m in lines)  # nested → indented
    assert any(m.startswith("outer:") for m in lines)
    # Only the root scope reaches the event stream.
    recs = events.read_events(path)
    assert [r["name"] for r in recs if r["kind"] == "timing"] == ["outer"]


def test_debug_time_as_decorator():
    from tpu_resiliency.utils.timers import debug_time

    @debug_time("work")
    def f(x):
        return x + 1

    @debug_time
    def g(x):
        return x * 2

    assert f(1) == 2 and g(3) == 6
