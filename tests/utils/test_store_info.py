"""store_info CLI + the keys/barriers introspection ops against a live server."""

import io
import threading
import time

from tpu_resiliency.platform.store import KVClient, KVServer
from tpu_resiliency.tools import store_info


def test_introspection_ops_and_report():
    server = KVServer(host="127.0.0.1", port=0)
    try:
        c = KVClient("127.0.0.1", server.port)
        c.set("launcher/jobs/a", 1)
        c.set("launcher/jobs/b", {"payload": "x" * 1000})
        c.set("hb/r0", "t")
        c.touch("hb/r0")

        # keys: names only, prefix-scoped, sorted.
        assert c.keys("launcher/") == ["launcher/jobs/a", "launcher/jobs/b"]
        assert len(c.keys()) == 3

        # A rank parked in a world-2 barrier: the report must show who's missing.
        def late_barrier():
            c2 = KVClient("127.0.0.1", server.port)
            try:
                c2.barrier_join("iter/0/barrier", rank=0, world_size=2, timeout=10.0)
            except Exception:
                pass
            finally:
                c2.close()

        t = threading.Thread(target=late_barrier, daemon=True)
        t.start()
        deadline = time.time() + 5
        while "iter/0/barrier" not in c.barrier_names() and time.time() < deadline:
            time.sleep(0.02)
        assert c.barrier_names() == ["iter/0/barrier"]

        out = io.StringIO()
        store_info.report(c, prefix="", stale_prefix="hb/", max_age=30.0, out=out)
        text = out.getvalue()
        assert "ping: ok" in text
        assert "keys: 3 total (3 in store)" in text
        assert "launcher/: 2" in text and "hb/: 1" in text
        assert "barriers: 1 live" in text
        assert "iter/0/barrier: 1/2 (waiting on 1; gen 0, arrived [0])" in text
        assert "stale under 'hb/' (>30s): none" in text

        # Unblock the parked rank so teardown is clean.
        c.barrier_join("iter/0/barrier", rank=1, world_size=2, timeout=10.0)
        t.join(timeout=10)
        c.close()
    finally:
        server.close()


def test_barriers_census_report():
    """--barriers renders the live census: waiter ages, MISSING, absent."""
    server = KVServer(host="127.0.0.1", port=0)
    try:
        c = KVClient("127.0.0.1", server.port)
        c.barrier_join("rdzv/round-3", rank=0, world_size=3, timeout=0.0, wait=False)
        c.barrier_join("rdzv/round-3", rank=2, world_size=3, timeout=0.0,
                       wait=False, on_behalf=True)
        out = io.StringIO()
        store_info.report_barriers(c, prefix="", out=out)
        text = out.getvalue()
        assert "open barrier rounds: 1" in text
        assert "rdzv/round-3" in text and "1/3 arrived" in text
        assert "r0 waiting" in text
        assert "MISSING: [1]" in text
        assert "absent (proxied dead): [2]" in text
        # CLI flag wiring: exit 0, same content.
        assert store_info.main([f"127.0.0.1:{server.port}", "--barriers"]) == 0
        c.close()
    finally:
        server.close()


def test_cli_main_against_live_and_dead_endpoints(capsys):
    server = KVServer(host="127.0.0.1", port=0)
    try:
        seed = KVClient("127.0.0.1", server.port)
        seed.set("x/y", 1)
        seed.close()
        assert store_info.main([f"127.0.0.1:{server.port}"]) == 0
        text = capsys.readouterr().out
        assert "ping: ok" in text and "x/: 1" in text
    finally:
        server.close()
    # Dead endpoint: fail fast with a message, not the 60-retry ladder.
    t0 = time.monotonic()
    assert store_info.main([f"127.0.0.1:{server.port}"]) == 1
    assert time.monotonic() - t0 < 30.0
    assert "cannot connect" in capsys.readouterr().err
    # Malformed endpoint exits 2 via argparse.
    try:
        store_info.main(["nonsense"])
        raise AssertionError("argparse must reject a portless endpoint")
    except SystemExit as e:
        assert e.code == 2


def test_stats_flag_renders_live_op_table(capsys):
    server = KVServer(host="127.0.0.1", port=0)
    try:
        c = KVClient("127.0.0.1", server.port)
        for i in range(120):
            c.set(f"hb/r{i % 4}", i)
            c.get("hb/r0", timeout=1.0)
        c.close()
        assert store_info.main([f"127.0.0.1:{server.port}", "--stats"]) == 0
        text = capsys.readouterr().out
        assert "store stats" in text
        assert "set" in text and "get" in text
        assert "hot key prefixes" in text and "hb/r0" in text
        assert "dedup:" in text
    finally:
        server.close()


def test_stats_flag_exit_codes(capsys):
    # Disabled stats: message + exit 1.
    server = KVServer(host="127.0.0.1", port=0, stats_enabled=False)
    try:
        assert store_info.main([f"127.0.0.1:{server.port}", "--stats"]) == 1
        assert "disabled" in capsys.readouterr().out
    finally:
        server.close()
    # Unreachable store: exit 1 (the existing fail-fast path).
    assert store_info.main([f"127.0.0.1:{server.port}", "--stats"]) == 1


def test_stats_flag_against_pre_telemetry_server(capsys, monkeypatch):
    """Version skew: an old server answers unknown-op; the CLI reports and
    exits 1 in one round trip (no retry ladder)."""
    monkeypatch.setattr(KVServer, "_op_store_stats", None)
    server = KVServer(host="127.0.0.1", port=0)
    try:
        t0 = time.monotonic()
        assert store_info.main([f"127.0.0.1:{server.port}", "--stats"]) == 1
        assert time.monotonic() - t0 < 2.0
        assert "pre-telemetry" in capsys.readouterr().err
    finally:
        server.close()
