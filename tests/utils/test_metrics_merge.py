"""Merge algebra of MetricsRegistry snapshots.

The push-path contract: counters sum, gauges are last-writer-wins by
timestamp, histograms add bucket-wise — and the merge is associative AND
commutative, so a tree of partial merges (what an O(log N) aggregation
topology produces) equals the flat merge, and either equals the flat
``aggregate()`` of the same event stream.
"""

import random

import pytest

from tpu_resiliency.utils.metrics import (
    DEFAULT_BUCKETS,
    MetricsRegistry,
    aggregate,
    observe_record,
)


def _exposition_series(reg: MetricsRegistry) -> dict:
    """Counter/gauge values and histogram buckets, quantiles excluded (the
    merged truth is buckets; reservoirs don't transport)."""
    out = {}
    snap = reg.snapshot()
    for name, entries in snap["metrics"].items():
        for e in entries:
            key = (name, tuple(sorted(e["labels"].items())))
            if e["type"] == "histogram":
                # Buckets and counts compare EXACTLY; the float ``sum``
                # accumulator is normalized (addition order varies with merge
                # shape, the one place IEEE754 non-associativity leaks in).
                out[key] = ("histogram", e["count"], round(e["sum"], 6),
                            tuple(e["buckets"]["bounds"]),
                            tuple(e["buckets"]["counts"]))
            else:
                out[key] = (e["type"], round(e["value"], 6))
    return out


def _random_registry(seed: int) -> MetricsRegistry:
    rng = random.Random(seed)
    reg = MetricsRegistry()
    for i in range(rng.randrange(1, 5)):
        reg.counter("m_total", "c", kind=f"k{rng.randrange(3)}").inc(
            rng.randrange(1, 100)
        )
    for i in range(rng.randrange(1, 4)):
        reg.gauge("g_val", "g", slot=f"s{rng.randrange(2)}").set(
            rng.randrange(100), ts=rng.randrange(1, 1000)
        )
    h = reg.histogram("h_seconds", "h")
    for _ in range(rng.randrange(0, 20)):
        h.observe(rng.random() * 100)
    return reg


def merged(*snaps) -> MetricsRegistry:
    reg = MetricsRegistry()
    for s in snaps:
        reg.merge(s)
    return reg


def test_counters_sum_and_gauges_lww():
    a, b = MetricsRegistry(), MetricsRegistry()
    a.counter("c_total").inc(3)
    b.counter("c_total").inc(4)
    a.gauge("g").set(10, ts=100.0)
    b.gauge("g").set(20, ts=50.0)  # older write must lose
    m = merged(a.snapshot(), b.snapshot())
    assert m.counter("c_total").value == 7
    assert m.gauge("g").value == 10  # newest ts wins regardless of order
    m2 = merged(b.snapshot(), a.snapshot())
    assert m2.gauge("g").value == 10


def test_histograms_add_bucketwise():
    a, b = MetricsRegistry(), MetricsRegistry()
    for v in (0.05, 0.5):
        a.histogram("h_seconds", "", (0.1, 1.0)).observe(v)
    for v in (0.5, 5.0):
        b.histogram("h_seconds", "", (0.1, 1.0)).observe(v)
    m = merged(a.snapshot(), b.snapshot())
    h = next(iter(m.histograms("h_seconds").values()))
    assert h.count == 4 and abs(h.sum - 6.05) < 1e-9
    assert h.bucket_counts == [1, 2, 1]


def test_bucket_bounds_mismatch_is_an_error():
    a, b = MetricsRegistry(), MetricsRegistry()
    a.histogram("h_seconds", "", (0.1, 1.0)).observe(0.5)
    b.histogram("h_seconds", "", (0.2, 2.0)).observe(0.5)
    m = MetricsRegistry()
    m.merge(a.snapshot())
    with pytest.raises(ValueError, match="bounds mismatch"):
        m.merge(b.snapshot())


@pytest.mark.parametrize("seed", [0, 1, 2, 3, 4, 5, 6, 7])
def test_merge_is_associative_and_commutative(seed):
    """Property-style: for random registries A, B, C every merge order and
    every tree shape yields the identical exposition state."""
    rng = random.Random(seed)
    snaps = [
        _random_registry(seed * 10 + i).snapshot() for i in range(3)
    ]
    a, b, c = snaps
    flat = _exposition_series(merged(a, b, c))
    # commutativity: all permutations
    for perm in ((a, c, b), (b, a, c), (b, c, a), (c, a, b), (c, b, a)):
        assert _exposition_series(merged(*perm)) == flat
    # associativity: (A+B)+C == A+(B+C) via partial-merge snapshots
    left = merged(merged(a, b).snapshot(), c)
    right = merged(a, merged(b, c).snapshot())
    assert _exposition_series(left) == flat
    assert _exposition_series(right) == flat
    # idempotent shape: re-snapshotting a merged registry loses nothing
    assert _exposition_series(merged(merged(a, b, c).snapshot())) == flat
    del rng


def _rank_stream(rank: int, n: int) -> list:
    rng = random.Random(rank)
    t = 1000.0 * (rank + 1)
    recs = []
    for i in range(n):
        t += rng.random()
        recs.append({"kind": "iteration_start", "iteration": i, "ts": t,
                     "pid": 100 + rank, "rank": rank})
        if rng.random() < 0.3:
            recs.append({"kind": "worker_failed", "ts": t, "pid": 100 + rank})
        if rng.random() < 0.3:
            recs.append({"kind": "span_end", "span": "rendezvous.round",
                         "duration_s": rng.random(), "ts": t, "pid": 100 + rank})
    return recs


def test_tree_merged_rank_snapshots_equal_flat_aggregate():
    """The ISSUE's parity criterion: per-rank registries (what each rank's
    MetricsPublisher pushes), merged as a tree, must equal the flat
    ``aggregate()`` of the concatenated event stream — counters and
    histogram buckets identical."""
    streams = {r: _rank_stream(r, 25) for r in range(4)}
    # per-rank live registries (what each rank pushes)
    rank_snaps = []
    for r, recs in streams.items():
        reg = MetricsRegistry()
        for rec in recs:
            observe_record(rec, reg)
        rank_snaps.append(reg.snapshot())
    # tree: ((r0+r1) + (r2+r3))
    tree = merged(
        merged(rank_snaps[0], rank_snaps[1]).snapshot(),
        merged(rank_snaps[2], rank_snaps[3]).snapshot(),
    )
    # flat post-hoc aggregation of the combined stream
    flat_reg = aggregate([rec for recs in streams.values() for rec in recs])
    tree_series = _exposition_series(tree)
    flat_series = _exposition_series(flat_reg)
    # Gauges carry live wall-clock write stamps; drop them (LWW across
    # processes is a freshness rule, not a replay-stable value) and compare
    # every counter and histogram exactly.
    tree_cmp = {k: v for k, v in tree_series.items() if v[0] != "gauge"}
    flat_cmp = {k: v for k, v in flat_series.items() if v[0] != "gauge"}
    assert tree_cmp == flat_cmp
    # The step histogram specifically: bucket-identical.
    th = next(iter(tree.histograms("tpu_step_seconds").values()))
    fh = next(iter(flat_reg.histograms("tpu_step_seconds").values()))
    assert th.bucket_counts == fh.bucket_counts and th.count == fh.count
    assert th.bounds == tuple(fh.bounds)


def test_merge_rejects_garbage():
    reg = MetricsRegistry()
    with pytest.raises(ValueError):
        reg.merge({"not": "a snapshot"})
    # Tolerates a pre-merge-format histogram entry (no buckets): skipped,
    # not crashed.
    reg.merge({"ts": 0, "metrics": {
        "h_seconds": [{"type": "histogram", "labels": {}, "count": 3, "sum": 1.0}],
        "c_total": [{"type": "counter", "labels": {}, "value": 2}],
    }})
    assert reg.counter("c_total").value == 2
    assert not reg.histograms("h_seconds")


def test_job_label_injection_keeps_jobs_separate():
    """REGRESSION (fleet federation): merging two jobs' snapshots into one
    fleet registry must not sum their same-named series — the injected job
    label keeps tpu_restarts_total{job="a"} and {job="b"} distinct — while an
    unlabelled merge of the same snapshots (the explicit fleet-total family)
    still sums them."""
    a, b = MetricsRegistry(), MetricsRegistry()
    a.counter("tpu_restarts_total", "restarts", layer="injob").inc(3)
    b.counter("tpu_restarts_total", "restarts", layer="injob").inc(5)
    fleet = MetricsRegistry()
    fleet.merge(a.snapshot(), extra_labels={"job": "a"})
    fleet.merge(b.snapshot(), extra_labels={"job": "b"})
    assert fleet.counter("tpu_restarts_total", "", layer="injob", job="a").value == 3
    assert fleet.counter("tpu_restarts_total", "", layer="injob", job="b").value == 5
    prom = fleet.to_prometheus()
    assert 'job="a"' in prom and 'job="b"' in prom
    totals = merged(a.snapshot(), b.snapshot())
    assert totals.counter("tpu_restarts_total", "", layer="injob").value == 8


def test_job_label_injection_overrides_and_stays_associative():
    """extra_labels override a same-named snapshot label (a job cannot forge
    its fleet identity), and a tree of labelled partial merges equals the
    flat labelled merge."""
    a = MetricsRegistry()
    a.counter("c_total", "", job="forged").inc(2)
    fleet = MetricsRegistry()
    fleet.merge(a.snapshot(), extra_labels={"job": "real"})
    assert fleet.counter("c_total", "", job="real").value == 2
    # tree == flat through a partial labelled merge's snapshot
    b = MetricsRegistry()
    b.counter("c_total").inc(7)
    partial = MetricsRegistry()
    partial.merge(b.snapshot(), extra_labels={"job": "b"})
    tree = MetricsRegistry()
    tree.merge(partial.snapshot())
    flat = MetricsRegistry()
    flat.merge(b.snapshot(), extra_labels={"job": "b"})
    assert _exposition_series(tree) == _exposition_series(flat)


def test_default_buckets_roundtrip_through_json():
    """Bounds survive a JSON round-trip (floats stay equal) so merging a
    store-transported snapshot never false-positives the mismatch check."""
    import json

    reg = MetricsRegistry()
    reg.histogram("h_seconds").observe(0.3)
    snap = json.loads(json.dumps(reg.snapshot()))
    m = MetricsRegistry()
    m.merge(snap)
    h = next(iter(m.histograms("h_seconds").values()))
    assert h.bounds == DEFAULT_BUCKETS and h.count == 1
