"""Byte-flow ledger: purpose attribution by transfer tag, family
reconciliation, residue accounting, publish→metrics parity, and the
``tpu-metrics-dump --bytes`` CLI."""

import io
import json

import pytest

from tpu_resiliency.tools import metrics_dump
from tpu_resiliency.utils.byteflow import (
    ByteFlowLedger,
    render_table,
    tag_purpose,
)
from tpu_resiliency.utils.metrics import aggregate


def _records():
    return [
        {"kind": "p2p_transfer", "direction": "send", "bytes": 1000,
         "dst": 1, "tag": "repl/3"},
        {"kind": "p2p_transfer", "direction": "recv", "bytes": 1000,
         "src": 0, "tag": "repl/3"},
        {"kind": "p2p_transfer", "direction": "recv", "bytes": 512,
         "src": 2, "tag": "remir/0"},
        {"kind": "p2p_transfer", "direction": "recv", "bytes": 500,
         "src": 2, "tag": "retr/1"},
        {"kind": "p2p_transfer", "direction": "recv", "bytes": 300,
         "src": 3, "tag": "rread/0/1"},
        {"kind": "p2p_transfer", "direction": "recv", "bytes": 200, "src": 2},
        {"kind": "reshard_fetch", "via": "peer", "holder": 2, "bytes": 256},
        {"kind": "reshard_fetch", "via": "local", "bytes": 700},
        {"kind": "ckpt_write_file", "container": "main", "bytes": 4096},
        {"kind": "store_stats", "bytes_in": 100, "bytes_out": 150,
         "ops": {"set": 3}},
    ]


def test_tag_purposes():
    assert tag_purpose("repl/3") == "replicate"
    assert tag_purpose("remir/0") == "replicate"
    assert tag_purpose("retr/1") == "retrieve"
    assert tag_purpose("rread/0/7") == "reshard"
    assert tag_purpose(None) == "unknown"
    assert tag_purpose("mystery/1") == "unknown"


def test_summary_attribution_and_residue():
    led = ByteFlowLedger()
    led.observe_many(_records())
    s = led.summary()
    assert s["schema"] == "tpu-byteflow-1"
    assert s["by_purpose"]["replicate"] == 2512
    assert s["by_purpose"]["retrieve"] == 500
    assert s["by_purpose"]["reshard"] == 300 + 256 + 700
    assert s["by_purpose"]["ckpt_write"] == 4096
    assert s["by_purpose"]["store"] == 250
    assert s["by_purpose"]["unknown"] == 200
    assert s["residue_bytes"] == 200
    assert s["total_bytes"] == sum(s["by_purpose"].values())
    assert 0.0 < s["accounted_frac"] < 1.0
    # p2p family: total includes the unknown-tag frame; others fully account.
    fam = s["families"]["p2p"]
    assert fam["total"] == 2512 + 500 + 300 + 200
    assert fam["residue"] == 200
    assert s["families"]["ckpt_write"]["residue"] == 0
    # peer dimension survives into flows.
    peers = {(f["purpose"], f["peer"]) for f in s["flows"]}
    assert ("replicate", "r1") in peers and ("reshard", "r2") in peers


def test_reconcile_matches_counter_families():
    recs = _records()
    led = ByteFlowLedger()
    led.observe_many(recs)
    recon = led.reconcile(aggregate(recs))
    # Both sides consume the identical stream: zero drift everywhere.
    for name, row in recon.items():
        assert row["drift_bytes"] == 0, (name, row)
    assert recon["p2p"]["counter_bytes"] == 2512 + 500 + 300 + 200
    assert recon["store"]["counter_bytes"] == 250


def test_publish_deltas_reach_metrics_and_never_double():
    led = ByteFlowLedger()
    led.observe_many(_records())
    pub = []
    rec = lambda source, kind, **p: pub.append({"kind": kind, **p})  # noqa: E731
    led.publish(rec)
    led.publish(rec)  # nothing new moved: no second event
    assert len(pub) == 1
    prom = aggregate(pub).to_prometheus()
    assert 'tpu_byteflow_bytes_total{direction="recv",purpose="replicate"}' in prom
    assert "tpu_byteflow_residue_bytes 200" in prom
    assert "tpu_byteflow_accounted_ratio" in prom
    # More traffic → one more event with only the delta.
    led.observe({"kind": "ckpt_write_file", "container": "main", "bytes": 10})
    led.publish(rec)
    assert len(pub) == 2
    assert pub[1]["flows"] == {"ckpt_write/write": 10}


def test_own_narration_is_not_evidence():
    led = ByteFlowLedger()
    led.observe({"kind": "byteflow_update", "flows": {"replicate/send": 999}})
    assert led.summary()["total_bytes"] == 0


def test_render_table_mentions_everything(capsys):
    led = ByteFlowLedger()
    led.observe_many(_records())
    out = io.StringIO()
    render_table(led.summary(), out=out)
    text = out.getvalue()
    for want in ("byte flow:", "replicate", "reshard", "ckpt_write",
                 "tpu_ckpt_replication_bytes_total", "residue"):
        assert want in text, text


# -- CLI ----------------------------------------------------------------------


def _write_events(tmp_path):
    path = tmp_path / "ev.jsonl"
    with open(path, "w") as f:
        for rec in _records():
            f.write(json.dumps({"ts": 1.0, "source": "t", "pid": 1, **rec}) + "\n")
    return str(path)


def test_metrics_dump_bytes_table(tmp_path, capsys):
    path = _write_events(tmp_path)
    assert metrics_dump.main([path, "--bytes"]) == 0
    out = capsys.readouterr().out
    assert "byte flow:" in out and "replicate" in out
    assert "counter drift 0 B" in out


def test_metrics_dump_bytes_json(tmp_path, capsys):
    path = _write_events(tmp_path)
    assert metrics_dump.main([path, "--bytes", "--format", "json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["schema"] == "tpu-byteflow-1"
    assert doc["residue_bytes"] == 200
    assert doc["reconcile"]["p2p"]["drift_bytes"] == 0


def test_metrics_dump_bytes_conflicts(tmp_path, capsys):
    path = _write_events(tmp_path)
    assert metrics_dump.main([path, "--bytes", "--goodput"]) == 2
    assert metrics_dump.main(
        [path, "--bytes", "--goodput", "--baseline", path]
    ) == 2
