"""Goodput ledger: interval algebra, phase attribution, publish parity, CLI."""

import json

import pytest

from tpu_resiliency.utils import events
from tpu_resiliency.utils.goodput import (
    GoodputLedger,
    merge_intervals,
    render_table,
    subtract_intervals,
    total_seconds,
)
from tpu_resiliency.utils.metrics import MetricsRegistry, aggregate


@pytest.fixture(autouse=True)
def clean_sinks():
    events.clear_sinks()
    yield
    events.clear_sinks()


# -- interval algebra ---------------------------------------------------------


def test_interval_algebra():
    assert merge_intervals([]) == []
    assert merge_intervals([(0, 1), (0.5, 2), (3, 4)]) == [(0, 2), (3, 4)]
    assert merge_intervals([(1, 1), (2, 1)]) == []  # empty/backward dropped
    assert subtract_intervals([(0, 10)], [(2, 3), (5, 7)]) == [
        (0, 2), (3, 5), (7, 10)
    ]
    assert subtract_intervals([(0, 2), (3, 5)], [(1, 4)]) == [(0, 1), (4, 5)]
    assert subtract_intervals([(0, 5)], [(0, 10)]) == []
    assert subtract_intervals([(0, 5)], []) == [(0, 5)]
    assert total_seconds([(0, 2), (3, 4.5)]) == 3.5


# -- attribution --------------------------------------------------------------


T0 = 10_000.0


def _step(i, ts, pid=10, rank=0):
    return {"kind": "iteration_start", "iteration": i, "ts": ts,
            "pid": pid, "rank": rank}


def test_phases_partition_wall_clock_exactly():
    led = GoodputLedger()
    led.observe_many([
        {"kind": "span_end", "span": "rendezvous.round", "ts": T0 + 2,
         "duration_s": 2.0, "pid": 1},
        *[_step(i, T0 + 2 + i) for i in range(4)],       # train 2..5
        {"kind": "ckpt_foreground_blocked", "ts": T0 + 5.5,
         "duration_s": 1.0, "pid": 10, "rank": 0},       # stall 4.5..5.5
        {"kind": "incident_opened", "incident_id": "i1", "ts": T0 + 6, "pid": 1},
        {"kind": "incident_closed", "incident_id": "i1", "ts": T0 + 8, "pid": 1},
    ])
    s = led.summary()
    assert s["wall_clock_s"] == pytest.approx(8.0)
    assert sum(s["phases"].values()) == pytest.approx(s["wall_clock_s"])
    # The stall window [4.5, 5.5] outranks the train interval it overlaps.
    assert s["phases"]["train"] == pytest.approx(2.5)
    assert s["phases"]["ckpt_stall"] == pytest.approx(1.0)
    assert s["phases"]["restart"] == pytest.approx(2.0)
    assert s["phases"]["incident"] == pytest.approx(2.0)
    assert s["phases"]["unattributed"] == pytest.approx(0.5)
    assert s["goodput_ratio"] == pytest.approx(2.5 / 8.0)
    assert s["steps"] == 3
    assert s["ranks"]["0"]["steps"] == 3
    assert s["ranks"]["0"]["train_s"] == pytest.approx(3.0)  # raw, pre-overlap


def test_overlapping_evidence_never_double_counts():
    """A sync save emits BOTH ckpt_foreground_blocked and its per-phase
    timings over the same window: interval union must charge the window
    once."""
    led = GoodputLedger()
    led.observe_many([
        _step(0, T0),
        {"kind": "ckpt_foreground_blocked", "ts": T0 + 2.0, "duration_s": 2.0,
         "pid": 10, "rank": 0},
        {"kind": "timing", "name": "ckpt.save.serialize", "ts": T0 + 1.0,
         "duration_s": 1.0, "pid": 10, "rank": 0},
        {"kind": "timing", "name": "ckpt.save.write", "ts": T0 + 2.0,
         "duration_s": 1.0, "pid": 10, "rank": 0},
        {"kind": "span_end", "span": "ckpt.save.enqueue", "ts": T0 + 2.0,
         "duration_s": 2.0, "pid": 10, "rank": 0},
        _step(1, T0 + 3.0),
    ])
    s = led.summary()
    assert s["phases"]["ckpt_stall"] == pytest.approx(2.0)  # once, not 6s
    assert s["phases"]["train"] == pytest.approx(1.0)  # 0..3 minus the stall
    assert sum(s["phases"].values()) == pytest.approx(s["wall_clock_s"])


def test_step_gating_matches_metrics_bridge():
    """Repeated iterations (in-process restart) and over-cap gaps are not
    steps — the same rule observe_record applies to tpu_step_seconds."""
    led = GoodputLedger(max_step_s=10.0)
    led.observe_many([
        _step(0, T0), _step(1, T0 + 1),          # one step
        _step(1, T0 + 5),                        # repeat: not a step
        _step(2, T0 + 30),                       # 25s > cap: not a step
        _step(3, T0 + 31),                       # one step
    ])
    s = led.summary()
    assert s["steps"] == 2
    assert s["phases"]["train"] == pytest.approx(2.0)


def test_fault_to_resume_window_is_restart():
    """The operator-visible restart cost — failure detection, teardown,
    respawn, the new interpreter's imports — is the fault-evidence →
    training-resumed window, not just the instrumented spans."""
    led = GoodputLedger()
    led.observe_many([
        _step(0, T0), _step(1, T0 + 1),
        {"kind": "worker_failed", "ts": T0 + 1.5, "pid": 1},
        {"kind": "restart_requested", "ts": T0 + 1.6, "pid": 1},  # same window
        {"kind": "span_end", "span": "worker.spawn", "ts": T0 + 2.5,
         "duration_s": 0.1, "pid": 1},
        _step(0, T0 + 4.0, pid=11),  # respawned rank resumes: window closes
        _step(1, T0 + 5.0, pid=11),
    ])
    s = led.summary()
    assert s["phases"]["restart"] == pytest.approx(2.5)  # 1.5 -> 4.0
    assert s["phases"]["train"] == pytest.approx(1.0 + 1.0 - 0.0)
    assert sum(s["phases"].values()) == pytest.approx(s["wall_clock_s"])


def test_unresolved_restart_charged_to_end_of_stream():
    led = GoodputLedger()
    led.observe_many([
        _step(0, T0), _step(1, T0 + 1),
        {"kind": "worker_failed", "ts": T0 + 2, "pid": 1},
        {"kind": "budget_exhausted", "ts": T0 + 3, "pid": 1},
    ])
    s = led.summary()
    assert s["phases"]["restart"] == pytest.approx(1.0)  # 2 -> end (3)
    assert s["phases"]["train"] == pytest.approx(1.0)


def test_open_incident_charged_to_end_of_stream():
    led = GoodputLedger()
    led.observe_many([
        _step(0, T0),
        {"kind": "incident_opened", "incident_id": "i1", "ts": T0 + 1, "pid": 1},
        _step(1, T0 + 4),
    ])
    s = led.summary()
    assert s["phases"]["incident"] == pytest.approx(3.0)
    # train 0..4 loses the incident window 1..4
    assert s["phases"]["train"] == pytest.approx(1.0)


def test_incident_close_without_open_uses_time_to_recover():
    led = GoodputLedger()
    led.observe_many([
        _step(0, T0), _step(1, T0 + 10),
        {"kind": "incident_closed", "incident_id": "ix", "ts": T0 + 8,
         "time_to_recover_s": 3.0, "pid": 1},
    ])
    assert led.summary()["phases"]["incident"] == pytest.approx(3.0)


def test_empty_ledger_summary():
    s = GoodputLedger().summary()
    assert s["wall_clock_s"] == 0.0 and s["goodput_ratio"] == 0.0
    assert s["window"] is None and s["steps"] == 0


def test_publish_deltas_replay_to_identical_totals():
    """Live/post-hoc parity: aggregating the goodput_update records the
    ledger published reconstructs the same monotonic totals the final
    summary reports."""
    led = GoodputLedger()
    published = []
    rec = lambda src, kind, **p: published.append({"kind": kind, **p})

    led.observe_many([_step(i, T0 + i) for i in range(3)])
    led.publish(record=rec)
    led.observe_many([
        {"kind": "ckpt_foreground_blocked", "ts": T0 + 4, "duration_s": 1.0,
         "pid": 10, "rank": 0},
        _step(3, T0 + 5),
    ])
    led.publish(record=rec)
    led.publish(record=rec)  # no new evidence -> no new record
    assert len(published) == 2
    final = led.summary()
    reg = aggregate(published)
    totals = {
        e["labels"]["phase"]: e["value"]
        for e in reg.snapshot()["metrics"]["tpu_time_attributed_seconds_total"]
    }
    for phase, seconds in final["phases"].items():
        assert totals.get(phase, 0.0) == pytest.approx(seconds, abs=1e-5), phase
    assert reg.gauge("tpu_goodput_ratio").value == pytest.approx(
        final["goodput_ratio"]
    )


def test_publish_routes_through_events_by_default():
    led = GoodputLedger()
    led.observe_many([_step(0, T0), _step(1, T0 + 1)])
    seen = []
    events.add_sink(seen.append)
    led.publish()
    kinds = [e.kind for e in seen]
    assert kinds == ["goodput_update"]
    # And the ledger ignores its own narration when it comes back around.
    led.observe({"kind": "goodput_update", "ts": T0 + 999,
                 "phases": {"train": 1.0}})
    assert led.summary()["wall_clock_s"] == pytest.approx(1.0)


def test_render_table(capsys):
    led = GoodputLedger()
    led.observe_many([_step(i, T0 + i) for i in range(3)])
    render_table(led.summary())
    out = capsys.readouterr().out
    assert "goodput:" in out and "phase attribution" in out
    for phase in ("train", "ckpt_stall", "restart", "incident", "unattributed"):
        assert phase in out
    assert "per-rank:" in out and "rank 0:" in out


# -- CLI ----------------------------------------------------------------------


def test_metrics_dump_goodput_flag(tmp_path, capsys):
    from tpu_resiliency.tools import metrics_dump

    path = tmp_path / "ev.jsonl"
    with open(path, "w") as f:
        for rec in [
            _step(0, T0), _step(1, T0 + 1),
            {"kind": "span_end", "span": "worker.spawn", "ts": T0 + 0.2,
             "duration_s": 0.2, "pid": 1},
        ]:
            f.write(json.dumps(rec) + "\n")
    assert metrics_dump.main([str(path), "--goodput"]) == 0
    out = capsys.readouterr().out
    assert "goodput:" in out and "restart" in out
    assert metrics_dump.main([str(path), "--goodput", "--format", "json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["schema"] == "tpu-goodput-1"
    # spawn span [T0, T0+0.2] outranks the train interval [T0, T0+1]
    assert doc["phases"]["restart"] == pytest.approx(0.2)
    assert doc["phases"]["train"] == pytest.approx(0.8)
    assert sum(doc["phases"].values()) == pytest.approx(doc["wall_clock_s"])


# -- compare (autoscale PR) ---------------------------------------------------


def _summary_for(records):
    led = GoodputLedger()
    led.observe_many(records)
    return led.summary()


def test_compare_summaries_and_ledgers():
    from tpu_resiliency.utils.goodput import compare

    # Run A: 4 clean steps. Run B: same steps plus a 2 s restart window.
    a_recs = [_step(i, T0 + i) for i in range(5)]
    b_recs = [_step(0, T0), _step(1, T0 + 1),
              {"kind": "worker_failed", "ts": T0 + 1.5, "pid": 10},
              _step(2, T0 + 3.5), _step(3, T0 + 4.5)]
    led_a, led_b = GoodputLedger(), GoodputLedger()
    led_a.observe_many(a_recs)
    led_b.observe_many(b_recs)
    cmp_doc = compare(led_a, led_b)  # ledger inputs
    assert cmp_doc["schema"] == "tpu-goodput-compare-1"
    assert cmp_doc["ratio_delta"] > 0  # A trained a larger share of its wall
    assert cmp_doc["phases"]["restart"] == pytest.approx(-2.0)
    # Summary-document inputs answer identically.
    assert compare(led_a.summary(), led_b.summary()) == cmp_doc
    assert cmp_doc["steps_delta"] == 1


def test_compare_normalizes_wall_clock():
    """A controlled run that finishes sooner must not look worse for being
    shorter: the fractional deltas are per-wall-clock shares."""
    from tpu_resiliency.utils.goodput import compare

    short = _summary_for([_step(i, T0 + i * 0.5) for i in range(5)])  # 2 s
    long = _summary_for([_step(i, T0 + i) for i in range(5)])         # 4 s
    cmp_doc = compare(short, long)
    assert cmp_doc["phases"]["train"] == pytest.approx(-2.0)  # absolute
    assert cmp_doc["phase_frac"]["train"] == pytest.approx(0.0)  # share
    assert cmp_doc["ratio_delta"] == pytest.approx(0.0)


def test_render_compare(capsys):
    from tpu_resiliency.utils.goodput import compare, render_compare

    a = _summary_for([_step(i, T0 + i) for i in range(4)])
    b = _summary_for([_step(0, T0),
                      {"kind": "worker_failed", "ts": T0 + 1.2, "pid": 10},
                      _step(1, T0 + 3)])
    render_compare(compare(a, b), labels=("controlled", "baseline"))
    out = capsys.readouterr().out
    assert "controlled" in out and "baseline" in out
    assert "per-phase delta" in out and "train" in out and "restart" in out


def test_metrics_dump_goodput_baseline_flag(tmp_path, capsys):
    from tpu_resiliency.tools import metrics_dump

    run = tmp_path / "run.jsonl"
    base = tmp_path / "base.jsonl"
    with open(run, "w") as f:
        for rec in [_step(i, T0 + i) for i in range(4)]:
            f.write(json.dumps(rec) + "\n")
    with open(base, "w") as f:
        for rec in [_step(0, T0),
                    {"kind": "worker_failed", "ts": T0 + 1.0, "pid": 10},
                    _step(1, T0 + 3)]:
            f.write(json.dumps(rec) + "\n")
    assert metrics_dump.main(
        [str(run), "--goodput", "--baseline", str(base)]
    ) == 0
    out = capsys.readouterr().out
    assert "vs" in out and "delta" in out
    assert metrics_dump.main(
        [str(run), "--goodput", "--baseline", str(base), "--format", "json"]
    ) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["schema"] == "tpu-goodput-compare-1"
    assert doc["ratio_delta"] > 0
    # --baseline without --goodput is a usage error.
    assert metrics_dump.main([str(run), "--baseline", str(base)]) == 2
