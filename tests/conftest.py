"""Test harness configuration.

Forces JAX onto the host CPU platform with 8 virtual devices so multi-chip sharding,
mesh, and collective code paths run on any machine — the JAX analogue of the reference's
Gloo-on-CPU multi-process fixtures (``tests/straggler/unit/_utils.py:42-80``).
Must run before the first ``import jax`` anywhere in the test process.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"

# Subprocess-spawning tests (launcher e2e, WorkerGroup, layered restart) must be able
# to import tpu_resiliency from a fresh clone without a pip install: put the repo root
# on PYTHONPATH for every child this test session spawns.
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in os.environ.get("PYTHONPATH", "").split(os.pathsep):
    os.environ["PYTHONPATH"] = os.pathsep.join(
        p for p in (_REPO_ROOT, os.environ.get("PYTHONPATH", "")) if p
    )
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("TPU_RESILIENCY_LOG_LEVEL", "WARNING")

import jax  # noqa: E402

# A site-installed TPU plugin may have force-set jax_platforms at interpreter boot;
# override it back to CPU before any backend is initialized.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture
def kv_server():
    from tpu_resiliency.platform.store import KVServer

    server = KVServer(host="127.0.0.1", port=0)
    yield server
    server.close()


@pytest.fixture
def coord_store(kv_server):
    from tpu_resiliency.platform.store import CoordStore

    store = CoordStore("127.0.0.1", kv_server.port, timeout=30.0)
    yield store
    store.close()


@pytest.fixture
def tmp_uds_path(tmp_path):
    # Keep UDS paths short (108-byte sun_path limit).
    return str(tmp_path / "s.sock")
