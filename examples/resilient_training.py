"""End-to-end resilient training: launcher + callbacks + hierarchical checkpoints.

The full stack in one script (the analogue of the reference's
``examples/fault_tolerance/train_ddp_heartbeats_api.py`` + local-ckpt examples):

- launched by ``tpu-ft-launcher`` (in-job restart on worker death),
- FT heartbeats via :class:`FaultToleranceCallback` (hang detection),
- straggler section timing via :class:`StragglerDetectionCallback`,
- local checkpoints every 5 steps via :class:`HierarchicalCheckpointCallback`,
- resume-from-latest on every (re)start,
- a crash injected in round 0 at step 12 to demonstrate recovery.

Run::

    tpu-ft-launcher --nproc-per-node 1 --max-restarts 2 \\
        --warm-spares 1 \\
        --ft-param-initial_rank_heartbeat_timeout 60 \\
        --ft-param-rank_heartbeat_timeout 60 \\
        examples/resilient_training.py --steps 30 --ckpt-dir /tmp/resilient_ckpt

(``--warm-spares 1`` parks a pre-imported interpreter so the post-crash
respawn promotes it in tens of milliseconds instead of paying jax import.)
"""

from __future__ import annotations

import argparse
import os

# Allow running this file directly from a repo checkout (no pip install).
import os as _os, sys as _sys
_REPO_ROOT = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
if _REPO_ROOT not in _sys.path:
    _sys.path.insert(0, _REPO_ROOT)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"].split(",")[0])

import jax.numpy as jnp

from tpu_resiliency.checkpoint.local_manager import LocalCheckpointManager
from tpu_resiliency.integrations import (
    FaultToleranceCallback,
    HierarchicalCheckpointCallback,
    LoopContext,
    StragglerDetectionCallback,
    run_training,
)
from tpu_resiliency.launcher.errors import record


@record
def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--ckpt-dir", default="/tmp/resilient_ckpt")
    ap.add_argument("--crash-step", type=int, default=12)
    args = ap.parse_args()

    rank = int(os.environ.get("RANK", "0"))
    round_no = int(os.environ.get("TPU_FT_RESTART_COUNT", "0"))

    # -- model: tiny linear regression, jitted -----------------------------
    key = jax.random.PRNGKey(0)
    w0 = jax.random.normal(key, (16, 16))
    x = jax.random.normal(jax.random.PRNGKey(1), (32, 16))
    y = x @ jax.random.normal(jax.random.PRNGKey(2), (16, 16))

    @jax.jit
    def train_step(w, _):
        def loss_fn(w):
            return jnp.mean((x @ w - y) ** 2)

        g = jax.grad(loss_fn)(w)
        return w - 0.05 * g

    def step_fn(state, i):
        state = train_step(state, i)
        if round_no == 0 and rank == 0 and i == args.crash_step:
            raise RuntimeError(f"injected crash at step {i} (round 0)")
        return state

    # -- resiliency stack --------------------------------------------------
    mgr = LocalCheckpointManager(args.ckpt_dir, rank=rank)
    ckpt_cb = HierarchicalCheckpointCallback(
        local_manager=mgr,
        local_every=5,
        to_state_dict=lambda s: {"w": s},
        from_state_dict=lambda s, loaded: loaded["w"],
    )
    callbacks = [
        FaultToleranceCallback(calc_timeouts=True),
        # Full telemetry stack: section timing every step, plus sampled
        # profiler windows feeding per-program (prog/...) and per-op/scope
        # (op/...) device times into the scored matrix.
        StragglerDetectionCallback(
            report_time_interval=2.0, profile_programs_every=10, profile_ops=True
        ),
        ckpt_cb,
    ]

    ctx = LoopContext(rank=rank, state=w0)
    if ckpt_cb.restore_latest(ctx):
        print(f"[rank {rank}] round {round_no}: resumed from step {ctx.start_step}")
    ctx = run_training(step_fn, ctx.state, args.steps, callbacks=callbacks, ctx=ctx)
    final_loss = float(jnp.mean((x @ ctx.state - y) ** 2))
    ckpt_cb.close()
    print(f"[rank {rank}] round {round_no}: done at step {ctx.step}, loss {final_loss:.5f}")


if __name__ == "__main__":
    main()
