"""Layered in-job + in-process restart: both restarters on one workload.

The TPU-native analogue of the reference's
``examples/fault_tolerance/in_job_and_in_process_example.py``: a jitted train loop
wrapped with :class:`tpu_resiliency.inprocess.Wrapper` runs under ``tpu-ft-launcher``,
sharing the launcher-hosted coordination store (``TPU_RESILIENCY_STORE_EXTERNAL`` is
set by the agent, ``launcher/agent.py``). Fault routing:

- an **exception** inside the wrapped fn is absorbed by the in-process layer — the
  function restarts without the launcher noticing (no respawn, no budget charge);
- a **process death** escalates to the in-job layer — the launcher respawns the
  round, and the respawned wrappers form a fresh in-process restart world scoped by
  the new launcher round (``TPU_FT_RESTART_COUNT``).

Both layers narrate their state machines via the machine-parseable
``[NestedRestarter] name=[InJob|InProcess] state=...`` log-line contract
(reference ``rank_monitor_state_machine.py:127-145``, ``nested_restarter.py:34-107``).

Run (CPU simulation, 2 ranks)::

    TPU_RESILIENCY_LOG_LEVEL=INFO JAX_PLATFORMS=cpu \\
        tpu-ft-launcher --nproc-per-node 2 --max-restarts 2 --no-ft-monitors \\
        examples/layered_restart.py --steps 20
"""

from __future__ import annotations

import argparse
import os

# Allow running this file directly from a repo checkout (no pip install).
import os as _os, sys as _sys
_REPO_ROOT = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
if _REPO_ROOT not in _sys.path:
    _sys.path.insert(0, _REPO_ROOT)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

from tpu_resiliency.platform.device import apply_platform_env

apply_platform_env()  # the env var alone does not override the TPU plugin's boot config

import jax.numpy as jnp

from tpu_resiliency.inprocess import CallWrapper, Wrapper
from tpu_resiliency.inprocess.nested_restarter import NestedRestarter
from tpu_resiliency.launcher.errors import record


def build_train(args, rank: int, launcher_round: int):
    nr = NestedRestarter()

    @Wrapper(
        initialize=nr.on_initialize,
        abort=nr.on_abort,
        completion=nr.on_completion,
        terminate=nr.on_terminate,
        soft_timeout=30.0,
        hard_timeout=60.0,
    )
    def train(call: CallWrapper):
        @jax.jit
        def step(w, x):
            return w - 0.1 * jnp.tanh(w * x).mean(), (w * x).sum()

        w = jnp.ones(())
        for i in range(args.steps):
            # Fault (a): in round 0 the wrapper's first pass raises at --fail-step;
            # the in-process layer restarts the fn and iteration 1 runs clean.
            if (
                launcher_round == 0
                and call.iteration == 0
                and rank == 1
                and i == args.fail_step
            ):
                raise RuntimeError(f"transient fault at step {i}")
            # Fault (b): in round 0, the *restarted* fn dies hard at --die-step;
            # only the in-job layer can recover from a lost process.
            if (
                launcher_round == 0
                and call.iteration >= 1
                and rank == 1
                and i == args.die_step
            ):
                os._exit(17)
            w, loss = step(w, jnp.float32(i + 1))
            call.ping()
        return float(loss)

    return train


@record
def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--fail-step", type=int, default=5)
    ap.add_argument("--die-step", type=int, default=9)
    args = ap.parse_args()

    rank = int(os.environ.get("RANK", "0"))
    launcher_round = int(os.environ.get("TPU_FT_RESTART_COUNT", "0"))
    train = build_train(args, rank, launcher_round)
    loss = train()
    print(f"rank {rank}: finished (launcher round {launcher_round}, loss {loss})")


if __name__ == "__main__":
    main()
