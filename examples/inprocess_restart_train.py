"""End-to-end example: fault-tolerant JAX training with in-process restart.

The TPU-native analogue of the reference's
``examples/fault_tolerance/in_job_and_in_process_example.py`` + ``tests/inprocess/app.py``:
N rank processes train a jitted MLP; one rank is killed mid-run; the survivors restart
in place — abort device state, re-mesh to the shrunken world, reload the latest local
checkpoint — and finish training.

Run (CPU simulation, 2 ranks):

    python examples/inprocess_restart_train.py --world 2 --kill-rank 1 --kill-step 6

Each rank process:
  - wraps ``train`` with :class:`tpu_resiliency.inprocess.Wrapper`
  - saves a local checkpoint every ``--ckpt-every`` steps via
    :class:`~tpu_resiliency.checkpoint.LocalCheckpointManager`
  - on restart: reloads the newest fully-covered checkpoint and continues
"""

from __future__ import annotations

import argparse
import multiprocessing as mp
import os
import socket
import sys
import tempfile

# Allow running this file directly from a repo checkout (no pip install).
import os as _os, sys as _sys
_REPO_ROOT = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
if _REPO_ROOT not in _sys.path:
    _sys.path.insert(0, _REPO_ROOT)


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def rank_main(rank: int, world: int, port: int, args, result_q) -> None:
    os.environ.update(
        RANK=str(rank),
        WORLD_SIZE=str(world),
        TPU_RESILIENCY_STORE_HOST="127.0.0.1",
        TPU_RESILIENCY_STORE_PORT=str(port),
    )
    if args.cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"
    # Each rank process is a world of exactly --devices-per-rank devices (>1
    # certifies a surviving MULTI-device world re-entering, not just a lone
    # device). Must be pinned before the jax import below — and pinned even
    # for 1, since the caller's own XLA_FLAGS may force a different count.
    # Only the force-count flag is replaced; other inherited flags survive.
    kept = [
        t for t in os.environ.get("XLA_FLAGS", "").split()
        if not t.startswith("--xla_force_host_platform_device_count")
    ]
    kept.append(f"--xla_force_host_platform_device_count={args.devices_per_rank}")
    os.environ["XLA_FLAGS"] = " ".join(kept)
    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from tpu_resiliency.checkpoint import LocalCheckpointManager, PyTreeStateDict
    from tpu_resiliency.inprocess import (
        AbortCompilationCache,
        CallWrapper,
        JaxHealthCheck,
        RetryController,
        Wrapper,
    )

    ckpt_root = args.ckpt_root

    @Wrapper(
        initialize=RetryController(max_iterations=5),
        abort=AbortCompilationCache(),
        health_check=JaxHealthCheck(timeout=60.0),
        monitor_interval=0.1,
        last_call_wait=0.1,
        soft_timeout=5.0,
        hard_timeout=10.0,
        heartbeat_interval=0.25,
        heartbeat_timeout=5.0,
        barrier_timeout=60.0,
        completion_timeout=60.0,
    )
    def train(call: CallWrapper):
        fs = call.frozen_state
        my_rank, active_world = fs.active_rank, fs.active_world_size
        # Per-rank local checkpoints; comm-less here (each rank loads its own shard;
        # see tests/checkpoint for the replicated multi-rank flow).
        mgr = LocalCheckpointManager(ckpt_root, rank=fs.initial_rank)

        key = jax.random.PRNGKey(0)
        params = {
            "w1": jax.random.normal(key, (16, 32)) * 0.1,
            "w2": jax.random.normal(jax.random.fold_in(key, 1), (32, 1)) * 0.1,
        }
        start_step = 0
        latest = mgr.find_latest()
        if latest >= 0:
            tree, meta = mgr.load_tree(latest)
            params = tree["params"]
            start_step = int(meta["iteration"]) + 1
            print(f"[rank {fs.initial_rank}] resumed from step {start_step}", flush=True)

        batch_sharding = None
        if args.devices_per_rank > 1:
            # Shard the batch over this rank's own device mesh: every step the
            # surviving world completes is a genuinely multi-device program
            # (XLA partitions the matmuls and inserts the loss reduction).
            from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

            local_mesh = Mesh(np.asarray(jax.devices()), ("dp",))
            batch_sharding = NamedSharding(local_mesh, P("dp"))

        @jax.jit
        def step_fn(params, x, y):
            def loss_fn(p):
                h = jnp.tanh(x @ p["w1"])
                pred = h @ p["w2"]
                return jnp.mean((pred - y) ** 2)

            loss, grads = jax.value_and_grad(loss_fn)(params)
            new = jax.tree_util.tree_map(lambda p, g: p - 0.1 * g, params, grads)
            return new, loss

        rng = np.random.default_rng(123 + my_rank)
        loss = None
        for step in range(start_step, args.steps):
            if (
                fs.initial_rank == args.kill_rank
                and step == args.kill_step
                and fs.iteration == 0
            ):
                print(f"[rank {fs.initial_rank}] dying at step {step}", flush=True)
                os._exit(9)
            x = jnp.asarray(rng.standard_normal((8, 16)), dtype=jnp.float32)
            y = jnp.asarray(rng.standard_normal((8, 1)), dtype=jnp.float32)
            if batch_sharding is not None:
                x = jax.device_put(x, batch_sharding)
                y = jax.device_put(y, batch_sharding)
            params, loss = step_fn(params, x, y)
            call.ping()
            import time as _time

            _time.sleep(args.step_time)  # stand-in for a real training step
            if step % args.ckpt_every == 0:
                mgr.save(step, PyTreeStateDict({"params": params}), is_async=True)
                mgr.maybe_finalize()
        mgr.maybe_finalize(blocking=True)
        mgr.close()
        return {
            "rank": fs.initial_rank,
            "iteration": fs.iteration,
            "active_world": active_world,
            "local_devices": jax.local_device_count(),
            "final_loss": float(loss) if loss is not None else None,
            "resumed_from": start_step,
        }

    try:
        result = train()
        result_q.put((rank, result))
    except BaseException as e:  # noqa: BLE001
        result_q.put((rank, {"error": repr(e)}))
        raise


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--world", type=int, default=2)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--ckpt-every", type=int, default=2)
    ap.add_argument("--kill-rank", type=int, default=1)
    ap.add_argument("--kill-step", type=int, default=6)
    ap.add_argument("--step-time", type=float, default=0.25)
    ap.add_argument("--cpu", action="store_true", default=True)
    ap.add_argument("--ckpt-root", default=None)
    ap.add_argument(
        "--devices-per-rank", type=int, default=1,
        help="virtual devices per rank process: >1 certifies a surviving "
        "MULTI-device world re-entering after the restart",
    )
    args = ap.parse_args()
    if args.ckpt_root is None:
        args.ckpt_root = tempfile.mkdtemp(prefix="inproc-example-")

    port = free_port()
    ctx = mp.get_context("fork")
    q = ctx.Queue()
    procs = [
        ctx.Process(target=rank_main, args=(r, args.world, port, args, q))
        for r in range(args.world)
    ]
    for p in procs:
        p.start()
    results = {}
    import queue as qmod

    deadline = 180.0
    import time

    t0 = time.monotonic()
    while len(results) < args.world and time.monotonic() - t0 < deadline:
        try:
            rank, payload = q.get(timeout=1.0)
            results[rank] = payload
        except qmod.Empty:
            if all(not p.is_alive() for p in procs):
                break
    for p in procs:
        p.join(timeout=10)
        if p.is_alive():
            p.terminate()

    survivors = {
        r: v for r, v in results.items() if isinstance(v, dict) and "error" not in v
    }
    print("results:", results, flush=True)
    ok = bool(survivors) and all(
        v["iteration"] >= 1
        and v["resumed_from"] > 0
        and v["local_devices"] == args.devices_per_rank
        for v in survivors.values()
    )
    n_surv = len(survivors)
    print(
        f"RESTART-RESUME {'OK' if ok else 'FAILED'} "
        f"devices {args.world}x{args.devices_per_rank} -> "
        f"{n_surv}x{args.devices_per_rank}",
        flush=True,
    )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
