"""Mesh-sharded straggler telemetry as the product path: zero-gather report rounds.

The north-star configuration (BASELINE target 4/5): every worker is its own JAX
process (``jax.distributed``), and straggler report rounds ride the device mesh —
each process contributes its per-rank timing summary as a *shard* of a global mesh
array, the cross-rank reductions run as XLA collectives inside one compiled scoring
program, and the coordination store carries only the one-time column-name agreement.
No per-rank summary ever crosses the store (this script asserts that).

Contrast with the reference, which packs host dicts into tensors and runs
NCCL ``all_reduce`` + rank-0 ``gather`` with Python pack/unpack loops per report
(``straggler/reporting.py:255-296,338-419``).

Run (CPU simulation, 2 workers)::

    TPU_RESILIENCY_LOG_LEVEL=INFO tpu-ft-launcher --nproc-per-node 2 \\
        --no-ft-monitors examples/mesh_telemetry_training.py \\
        --coord-port 29620 --steps 150

On real TPU hosts, drop nothing: the same script scales — the mesh rides ICI/DCN.
"""

from __future__ import annotations

import argparse
import os
import time

# Allow running this file directly from a repo checkout (no pip install).
import os as _os, sys as _sys
_REPO_ROOT = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
if _REPO_ROOT not in _sys.path:
    _sys.path.insert(0, _REPO_ROOT)

# Default to the CPU simulation; a site plugin may have pre-set JAX_PLATFORMS to a
# platform workers can't initialize (e.g. a single-tenant TPU tunnel), so only an
# explicit TPU_MESH_EXAMPLE_PLATFORM wins over cpu here.
_platform = os.environ.get("TPU_MESH_EXAMPLE_PLATFORM", "cpu")
os.environ["JAX_PLATFORMS"] = _platform
# Each worker process simulates a 4-device host; the telemetry mesh uses one
# device per process (one row per rank).
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=4")

import jax

jax.config.update("jax_platforms", _platform)

import jax.numpy as jnp

from tpu_resiliency.integrations import LoopContext, run_training
from tpu_resiliency.integrations.straggler_callback import StragglerDetectionCallback
from tpu_resiliency.launcher.errors import record
from tpu_resiliency.platform.store import CoordStore, store_addr_from_env


@record
def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=150)
    ap.add_argument("--coord-port", type=int, required=True,
                    help="port for jax.distributed coordination (rank 0 hosts)")
    ap.add_argument("--slow-rank", type=int, default=1)
    ap.add_argument("--slow-ms", type=float, default=20.0)
    args = ap.parse_args()

    rank = int(os.environ["RANK"])
    world = int(os.environ["WORLD_SIZE"])
    jax.distributed.initialize(
        f"127.0.0.1:{args.coord_port}", num_processes=world, process_id=rank
    )

    host, port = store_addr_from_env()
    store = CoordStore(host, port)

    callback = StragglerDetectionCallback(
        report_time_interval=0.5,
        threshold=0.75,
        store=store.scoped("straggler/"),
        use_device_mesh=True,
    )

    @jax.jit
    def forward(w, x):
        return jnp.tanh(w @ x).sum()

    w = jnp.ones((64, 64))

    def step_fn(state, step):
        x = jnp.full((64, 8), 0.1 * (step % 7))
        loss = forward(w, x)
        loss.block_until_ready()
        # The injected straggler: this rank pays extra host time every step.
        if rank == args.slow_rank:
            time.sleep(args.slow_ms / 1e3)
        else:
            time.sleep(args.slow_ms / 4e3)
        return state

    ctx = run_training(
        step_fn,
        state=None,
        num_steps=args.steps,
        callbacks=[callback],
        ctx=LoopContext(rank=rank, world_size=world),
    )

    # --- the zero-gather proof -------------------------------------------------
    leaked = store.prefix_get("straggler/telemetry/round/")
    assert leaked == {}, f"per-rank summaries leaked through the store: {leaked}"
    report = callback.last_report
    if rank == 0:
        assert report is not None, "no report round elapsed; raise --steps"
        stragglers = report.identify_stragglers(perf_threshold=0.75)
        flagged = sorted(s.rank for s in stragglers.by_perf)
        assert flagged == [args.slow_rank], (flagged, report.perf_scores)
        print(
            f"ZERO-GATHER OK: report rounds rode the mesh; flagged ranks {flagged} "
            f"perf={report.perf_scores}",
            flush=True,
        )


if __name__ == "__main__":
    main()
