"""Resilient MoE training over the full (dp, pp, ep) mesh with in-process restart.

Demonstrates the framework's restart engine protecting its most complex workload:
a top-k routed mixture-of-experts model (``models/moe.py``) whose layer stack is
pipelined over the ``pp`` mesh axis and whose experts are sharded over ``ep``
(``parallel/pipeline.py``). A fault is injected mid-training; the in-process
restart loop catches it, re-enters the train function, and the loop resumes from
the newest local checkpoint — the compiled pipeline (microbatch schedule,
``ppermute`` stage ring, expert all-to-alls) is simply re-jitted on re-entry.

Run (single process, 8 virtual CPU devices):

    python examples/moe_pipeline_training.py --steps 12 --fault-step 5

Prints ``RESUMED step=<n>`` after the restart and ``DONE loss=<x>`` on success.
"""

from __future__ import annotations

import argparse
import os
import tempfile

# Allow running this file directly from a repo checkout (no pip install).
import os as _os, sys as _sys
_REPO_ROOT = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
if _REPO_ROOT not in _sys.path:
    _sys.path.insert(0, _REPO_ROOT)


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=12)
    p.add_argument("--fault-step", type=int, default=5)
    p.add_argument("--ckpt-every", type=int, default=2)
    p.add_argument("--ckpt-root", default=None)
    p.add_argument("--n-micro", type=int, default=2)
    p.add_argument(
        "--tpu", action="store_true",
        help="run on the real accelerator instead of 8 virtual CPU devices",
    )
    args = p.parse_args()

    if not args.tpu:
        # Force CPU hard: a site TPU plugin (or an inherited JAX_PLATFORMS) would
        # otherwise route the whole pipeline through one real chip.
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
    os.environ.setdefault("RANK", "0")
    os.environ.setdefault("WORLD_SIZE", "1")
    # Standalone single-rank run: host the coordination store on an ephemeral
    # port — the fixed default can be transiently busy on a shared host
    # (concurrent jobs/CI instances), and this example needs no fixed address.
    os.environ.setdefault("TPU_RESILIENCY_STORE_PORT", "0")

    import jax

    if not args.tpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax.sharding import NamedSharding

    from tpu_resiliency.checkpoint import LocalCheckpointManager, PyTreeStateDict
    from tpu_resiliency.inprocess.initialize import RetryController
    from tpu_resiliency.inprocess.wrap import CallWrapper, Wrapper
    from tpu_resiliency.models import moe
    from tpu_resiliency.parallel import mesh as pmesh
    from tpu_resiliency.parallel import pipeline as pl

    ckpt_root = args.ckpt_root or tempfile.mkdtemp(prefix="moe-pp-ckpt-")
    cfg = moe.MoEConfig.tiny(dtype=jnp.float32)
    fault_armed = {"armed": True}

    @Wrapper(
        initialize=RetryController(max_iterations=4),  # a persistent fault must not loop forever
        monitor_interval=0.05,
        last_call_wait=0.1,
        # First compile of the pipelined step is tens of seconds on CPU and the
        # watchdog's auto-heartbeat cannot tick inside it.
        soft_timeout=300.0,
        hard_timeout=600.0,
        heartbeat_interval=0.2,
        heartbeat_timeout=60.0,
        barrier_timeout=600.0,
        completion_timeout=600.0,
    )
    def train(call: CallWrapper):
        n_dev = len(jax.devices())
        split = pmesh.moe_pipeline_split(n_dev)
        mesh = pmesh.build_mesh(devices=jax.devices()[:n_dev], **split)
        specs = pmesh.moe_param_specs(cfg)
        specs["layers"] = pmesh.pipeline_layer_specs(specs["layers"])
        shardings = pmesh.tree_shardings(mesh, specs)

        params = jax.device_put(moe.init_params(jax.random.PRNGKey(0), cfg), shardings)
        tokens = jax.device_put(
            jnp.tile(jnp.arange(16, dtype=jnp.int32)[None], (split["dp"] * args.n_micro * 2, 3))[:, :32],
            NamedSharding(mesh, pmesh.batch_spec()),
        )

        with mesh:
            step, init_opt = pl.make_pipelined_train_step(
                cfg, mesh, n_micro=args.n_micro, family="moe"
            )
            step_jit = jax.jit(step)

            mgr = LocalCheckpointManager(ckpt_root, rank=0)
            start = 0
            latest = mgr.find_latest()
            if latest < 0:
                opt = jax.jit(init_opt)(params)
            else:
                # Restore params AND optimizer state — resuming with fresh Adam
                # moments would silently change the training trajectory. The
                # shardings pytree mirrors the saved tree; opt leaves use default
                # placement (None) and jit re-shards them on entry.
                opt_spec = jax.tree.map(lambda _: None, jax.eval_shape(init_opt, params))
                tree, meta = mgr.load_tree(
                    latest, shardings={"params": shardings, "opt": opt_spec}
                )
                params, opt = tree["params"], tree["opt"]
                start = int(meta["iteration"]) + 1
                print(f"RESUMED step={start}", flush=True)

            loss = None
            for i in range(start, args.steps):
                if fault_armed["armed"] and i == args.fault_step and call.frozen_state.iteration == 0:
                    fault_armed["armed"] = False
                    raise RuntimeError(f"injected fault at step {i}")
                params, opt, loss = step_jit(params, opt, tokens)
                if i % args.ckpt_every == 0:
                    mgr.save(
                        i, PyTreeStateDict({"params": params, "opt": opt}), is_async=False
                    )
            mgr.maybe_finalize(blocking=True)
            mgr.close()
            return float(loss)

    final = train()
    print(f"DONE loss={final:.4f}", flush=True)


if __name__ == "__main__":
    main()
