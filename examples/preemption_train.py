"""Preemption-synchronized final saves across a multi-controller job.

N rank processes train under ``jax.distributed`` (recoverable client,
``platform/distributed.py``); mid-run ONE rank receives the preemption notice
(SIGTERM — what a TPU maintenance event or spot reclaim delivers). The
coordination service broadcasts it, every rank observes the SAME agreed step,
saves that step through its LocalCheckpointManager, and stops cleanly with a
coordinator-last teardown. Re-running resumes from the synchronized step.

No reference analogue — this is TPU-first lifecycle the reference lacks.

Run (CPU simulation, 2 ranks; the parent SIGTERMs rank 1 after ~3 s):

    python examples/preemption_train.py --world 2
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import textwrap
import time

# Allow running this file directly from a repo checkout (no pip install).
import os as _os, sys as _sys
_REPO_ROOT = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
if _REPO_ROOT not in _sys.path:
    _sys.path.insert(0, _REPO_ROOT)


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


CHILD = textwrap.dedent(
    """
    import json, os, sys, time

    rank = int(sys.argv[1]); world = int(sys.argv[2])
    jd_port = sys.argv[3]; ckpt_root = sys.argv[4]
    import jax

    from tpu_resiliency.platform.device import apply_platform_env

    apply_platform_env()  # parent exports JAX_PLATFORMS for the simulation

    from tpu_resiliency.platform import distributed as jdist

    jdist.initialize(
        f"127.0.0.1:{jd_port}", num_processes=world, process_id=rank,
        heartbeat_timeout=10.0,
    )
    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from tpu_resiliency.checkpoint import LocalCheckpointManager, PyTreeStateDict
    from tpu_resiliency.integrations import PreemptionCheckpointCallback
    from tpu_resiliency.integrations.loop import LoopContext, run_training

    print(f"READY {rank}", flush=True)
    mgr = LocalCheckpointManager(ckpt_root, rank=rank)

    # The train state lives SHARDED on this rank's device mesh (the parent
    # exports 2 virtual devices per rank): the synchronized save captures a
    # mesh-sharded array, not a host scalar.
    local_mesh = Mesh(np.asarray(jax.local_devices()), ("dp",))
    shard = NamedSharding(local_mesh, P("dp"))

    def save(state, step):
        mgr.save(step, PyTreeStateDict({"w": state["w"]}), is_async=False)
        print(f"[rank {rank}] preemption save @ step {step}", flush=True)

    cb = PreemptionCheckpointCallback(on_preemption=save)

    @jax.jit
    def advance(w):
        return w + 1.0

    def step_fn(state, step):
        time.sleep(0.05)  # stand-in for a real train step
        return {"w": advance(state["w"])}

    ctx = LoopContext(rank=rank, world_size=world)
    ctx.state = {"w": jax.device_put(jnp.zeros((4, 2)), shard)}
    latest = mgr.find_latest()
    if latest >= 0:
        hollow, tensors, meta = mgr.load(latest)
        ctx.state = {"w": jax.device_put(jnp.asarray(tensors[0]), shard)}
        ctx.start_step = latest + 1
        print(f"[rank {rank}] resumed from step {ctx.start_step}", flush=True)
    ctx = run_training(step_fn, ctx.state, num_steps=400, callbacks=[cb], ctx=ctx)
    jdist.shutdown_graceful(rank, grace=3.0)  # coordinator-last teardown
    mgr.close()
    print(
        "PREEMPT " + json.dumps({"rank": rank, "stopped_at": ctx.step,
                                 "saved": cb.preempted_at}),
        flush=True,
    )
    """
)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--world", type=int, default=2)
    ap.add_argument("--ckpt-root", default=None)
    ap.add_argument(
        "--platform", default="cpu",
        help="JAX platform for the rank processes (default: cpu simulation)",
    )
    args = ap.parse_args()
    ckpt_root = args.ckpt_root or tempfile.mkdtemp(prefix="preempt-example-")
    print(f"[parent] checkpoints in {ckpt_root} (pass --ckpt-root here to resume)")
    jd_port = free_port()
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    child_env = {
        **os.environ,
        "JAX_PLATFORMS": args.platform,
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        # uninstalled checkouts: children run from a temp dir
        "PYTHONPATH": repo_root + os.pathsep + os.environ.get("PYTHONPATH", ""),
    }

    with tempfile.TemporaryDirectory(prefix="preempt-src-") as d:
        script = os.path.join(d, "child.py")
        with open(script, "w") as f:
            f.write(CHILD)
        import threading

        procs = []
        outputs: list[list[str]] = []
        readers: list[threading.Thread] = []
        for r in range(args.world):
            p = subprocess.Popen(
                [sys.executable, script, str(r), str(args.world), str(jd_port), ckpt_root],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
                env=child_env,
            )
            buf: list[str] = []
            t = threading.Thread(target=lambda p=p, b=buf: b.extend(p.stdout),
                                 daemon=True)
            t.start()
            procs.append(p)
            outputs.append(buf)
            readers.append(t)
        # Deliver the notice only once every rank is PAST jdist.initialize (the
        # preemption handler exists) — a SIGTERM before that just kills the rank.
        deadline = time.monotonic() + 120.0
        ready = False
        while time.monotonic() < deadline:
            ready = all(any(ln.startswith("READY") for ln in b) for b in outputs)
            if ready or any(p.poll() is not None for p in procs):
                break
            time.sleep(0.2)
        if not ready:
            # Never deliver the notice before the handler exists: a pre-READY
            # SIGTERM just kills the rank.
            for r, p in enumerate(procs):
                state = p.returncode if p.poll() is not None else "hung in startup"
                print(f"[parent] rank {r} not READY ({state}):")
                print("".join(outputs[r])[-1500:])
                if p.poll() is None:
                    p.kill()
            return 1
        time.sleep(2.0)  # everyone stepping
        print("[parent] delivering preemption notice (SIGTERM) to rank 1")
        procs[min(1, args.world - 1)].send_signal(signal.SIGTERM)
        saved_steps = set()
        ok = True
        for r, p in enumerate(procs):
            try:
                p.wait(timeout=120)
            except subprocess.TimeoutExpired:
                p.kill()
                ok = False
            readers[r].join(5.0)  # drain the tail before parsing
            out = "".join(outputs[r])
            got = False
            for ln in out.splitlines():
                if ln.startswith("PREEMPT "):
                    payload = json.loads(ln[len("PREEMPT "):])
                    saved_steps.add(payload["saved"])
                    print(f"[parent] rank {r}: {payload}")
                    got = True
            if not got or p.returncode != 0:
                print(f"[parent] rank {r} FAILED (rc={p.returncode}):")
                print(out[-1500:])
            ok = ok and got and p.returncode == 0
    ok = ok and len(saved_steps) == 1 and None not in saved_steps
    print(
        f"PREEMPTION-SYNC {'OK' if ok else 'FAILED'}: "
        f"all ranks saved step {saved_steps}"
    )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
